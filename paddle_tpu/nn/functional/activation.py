"""Activation functionals (parity:
/root/reference/python/paddle/nn/functional/activation.py). All map to VPU
elementwise ops; XLA fuses them into surrounding matmuls."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...decomposition import DecompAware
from ...framework.core import Tensor, apply

__all__ = [
    "relu", "relu6", "relu_", "elu", "selu", "celu", "gelu", "silu", "swish",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "tanh", "tanhshrink",
    "softshrink", "hardshrink", "leaky_relu", "prelu", "rrelu", "mish",
    "softplus", "softsign", "softmax", "log_softmax", "log_sigmoid", "glu",
    "maxout", "thresholded_relu", "gumbel_softmax",
]


def relu(x, name=None):
    return apply("relu", DecompAware("relu", jax.nn.relu), x)


def relu_(x, name=None):
    out = relu(x)
    x._value, x._node, x._out_idx = out._value, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def relu6(x, name=None):
    return apply("relu6", jax.nn.relu6, x)


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a: jax.nn.celu(a, alpha), x)


def gelu(x, approximate=False, name=None):
    return apply("gelu", DecompAware(
        "gelu", lambda a: jax.nn.gelu(a, approximate=approximate),
        approximate=approximate), x)


def silu(x, name=None):
    return apply("silu", DecompAware("silu", jax.nn.silu), x)


swish = silu


def sigmoid(x, name=None):
    return apply("sigmoid", DecompAware("sigmoid", jax.nn.sigmoid), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda a: jnp.clip(a, min, max), x)


def tanh(x, name=None):
    return apply("tanh", jnp.tanh, x)


def tanhshrink(x, name=None):
    return apply("tanhshrink", lambda a: a - jnp.tanh(a), x)


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink",
                 lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold,
                                               jnp.zeros_like(a))), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink",
                 lambda a: jnp.where(jnp.abs(a) > threshold, a,
                                     jnp.zeros_like(a)), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", DecompAware(
        "leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope),
        negative_slope=negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            w_b = w.reshape(())
        else:
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = -1
            w_b = w.reshape(shape)
        return jnp.where(a > 0, a, w_b * a)
    return apply("prelu", f, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...framework.core import default_generator
    if training:
        # key as positional arg, not closure cell — a captured per-call
        # key defeats the partial-capture segment cache (FC203)
        key = default_generator.next_key()
        def f(a, k):
            slope = jax.random.uniform(k, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply("rrelu", f, x, key)
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def mish(x, name=None):
    return apply("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus",
                 lambda a: jnp.where(beta * a > threshold, a,
                                     jax.nn.softplus(beta * a) / beta), x)


def softsign(x, name=None):
    return apply("softsign", jax.nn.soft_sign, x)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...framework import dtype as dtypes
        dtype = dtypes.convert_dtype(dtype)

    def f(a):
        if dtype is not None:
            a = a.astype(dtype)
        return jax.nn.softmax(a, axis=axis)
    return apply("softmax", DecompAware("softmax", f, axis=axis,
                                        dtype=dtype), x)


def log_softmax(x, axis=-1, dtype=None, name=None):
    def f(a):
        if dtype is not None:
            from ...framework import dtype as dtypes
            a = a.astype(dtypes.convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply("log_softmax", f, x)


def log_sigmoid(x, name=None):
    return apply("log_sigmoid", jax.nn.log_sigmoid, x)


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply("glu", f, x)


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply("maxout", f, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply("thresholded_relu",
                 lambda a: jnp.where(a > threshold, a, jnp.asarray(value, a.dtype)), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.core import default_generator
    key = default_generator.next_key()
    def f(a, k):
        g = jax.random.gumbel(k, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through estimator: forward emits the one-hot,
            # backward flows through the soft sample
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y
    return apply("gumbel_softmax", f, x, key)
