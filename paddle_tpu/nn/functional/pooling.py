"""Pooling functionals over lax.reduce_window (parity:
/root/reference/python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply, apply_nodiff

__all__ = [
    "max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d", "avg_pool2d",
    "avg_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _t(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(e) for e in v)


def _explicit_pads(padding, n, spatial, k, s, ceil_mode):
    """Resolve paddle's padding forms (int, per-dim, pair-list, 'SAME'/
    'VALID') plus ceil_mode into explicit per-dim (lo, hi) pairs. ceil
    mode adds high padding so reduce_window emits ceil((in+p-k)/s)+1
    windows (reference output-shape semantics)."""
    if isinstance(padding, str):
        m = padding.upper()
        if m == "VALID":
            pads = [(0, 0)] * n
        else:  # SAME
            pads = []
            for i in range(n):
                out = -(-spatial[i] // s[i])
                total = max((out - 1) * s[i] + k[i] - spatial[i], 0)
                pads.append((total // 2, total - total // 2))
    else:
        p = _t(padding, n) if not isinstance(padding, (list, tuple)) or \
            all(isinstance(e, int) for e in padding) else padding
        if isinstance(p, tuple) and len(p) == n:
            pads = [(e, e) for e in p]
        else:
            pads = [tuple(e) for e in p]
    if ceil_mode:
        out = []
        for i in range(n):
            lo, hi = pads[i]
            eff = spatial[i] + lo + hi - k[i]
            out_c = -(-eff // s[i]) + 1
            extra = (out_c - 1) * s[i] + k[i] - (spatial[i] + lo + hi)
            out.append((lo, hi + max(extra, 0)))
        pads = out
    return pads


def _pool(x, kernel, stride, padding, n, channel_last, kind, ceil_mode,
          exclusive=True):
    k = _t(kernel, n)
    s = _t(stride if stride is not None else kernel, n)

    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        window = (1, 1) + k
        strides = (1, 1) + s

    def f(a):
        spatial = a.shape[1:-1] if channel_last else a.shape[2:]
        pads = _explicit_pads(padding, n, spatial, k, s, ceil_mode)
        full_pads = ([(0, 0)] + pads + [(0, 0)]) if channel_last \
            else ([(0, 0), (0, 0)] + pads)
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
                else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window,
                                         strides, full_pads)
        # avg
        summed = jax.lax.reduce_window(
            a, 0.0 if jnp.issubdtype(a.dtype, jnp.floating) else 0,
            jax.lax.add, window, strides, full_pads)
        if exclusive and any(p != (0, 0) for p in pads):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                           window, strides, full_pads)
            return summed / counts
        denom = float(np.prod(k))
        return summed / denom

    return apply(f"{kind}_pool{n}d", f, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                "max", ceil_mode)
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, 1,
                                data_format == "NLC", ceil_mode)
        return out, idx
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                "max", ceil_mode)
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, 2,
                                data_format == "NHWC", ceil_mode)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                "max", ceil_mode)
    if return_mask:
        idx = _max_pool_indices(x, kernel_size, stride, padding, 3,
                                data_format == "NDHWC", ceil_mode)
        return out, idx
    return out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "avg", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "avg", ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "avg", ceil_mode, exclusive)


def _max_pool_indices(x, kernel, stride, padding, n, channel_last,
                      ceil_mode=False):
    """Flat spatial index (row-major over the input's spatial dims) of
    each window's max — the contract MaxUnPoolND consumes (reference
    return_mask semantics). Computed as a reduce_window argmax: the
    payload is (value, flat_index) and the reducer picks the larger
    value's index."""
    k = _t(kernel, n)
    s = _t(stride if stride is not None else kernel, n)

    def f(a):
        if channel_last:
            a = jnp.moveaxis(a, -1, 1)
        spatial = a.shape[2:]
        flat = jnp.arange(np.prod(spatial), dtype=jnp.int32).reshape(
            spatial)
        idx = jnp.broadcast_to(flat, a.shape)
        neg = jnp.iinfo(a.dtype).min if jnp.issubdtype(
            a.dtype, jnp.integer) else jnp.finfo(a.dtype).min
        dims = (1, 1) + tuple(k)
        strides = (1, 1) + tuple(s)
        pads = ((0, 0), (0, 0)) + tuple(
            _explicit_pads(padding, n, spatial, k, s, ceil_mode))

        def reducer(x1, x2):
            v1, i1 = x1
            v2, i2 = x2
            take2 = v2 > v1
            return (jnp.where(take2, v2, v1), jnp.where(take2, i2, i1))

        _, out_idx = jax.lax.reduce_window(
            (a, idx), (jnp.asarray(neg, a.dtype), jnp.asarray(0, jnp.int32)),
            reducer, dims, strides, pads)
        if channel_last:
            out_idx = jnp.moveaxis(out_idx, 1, -1)
        return out_idx.astype(jnp.int32)

    return apply_nodiff("max_pool_mask", f, x)


def _adaptive(x, output_size, n, kind, channel_last=False):
    out_sz = _t(output_size, n)

    def f(a):
        # spatial dims
        sp0 = a.ndim - n if channel_last is False else a.ndim - n - 1
        spatial = list(range(a.ndim - n, a.ndim)) if not channel_last else \
            list(range(a.ndim - n - 1, a.ndim - 1))
        out = a
        for d, (ax, o) in enumerate(zip(spatial, out_sz)):
            in_sz = out.shape[ax]
            if o == in_sz:
                continue
            if in_sz % o == 0:
                r = in_sz // o
                new_shape = out.shape[:ax] + (o, r) + out.shape[ax + 1:]
                resh = out.reshape(new_shape)
                out = jnp.max(resh, axis=ax + 1) if kind == "max" else \
                    jnp.mean(resh, axis=ax + 1)
            else:
                # general bins: start = floor(i*in/o), end = ceil((i+1)*in/o)
                pieces = []
                for i in range(o):
                    s0 = (i * in_sz) // o
                    e0 = -(-((i + 1) * in_sz) // o)
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(s0, e0)
                    seg = out[tuple(sl)]
                    red = jnp.max(seg, axis=ax, keepdims=True) if kind == "max" \
                        else jnp.mean(seg, axis=ax, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=ax)
        return out

    return apply(f"adaptive_{kind}_pool{n}d", f, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max")
