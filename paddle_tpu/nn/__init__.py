"""paddle_tpu.nn — parity with paddle.nn
(/root/reference/python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import quant  # noqa: F401
from . import initializer  # noqa: F401
from .layer.layers import Layer  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.moe import MoELayer, SwitchGate, GShardGate  # noqa: F401
from .layer.rnn import *  # noqa: F401,F403
from .layer.extras import *  # noqa: F401,F403
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from . import utils  # noqa: F401
from ..optimizer.clip import (  # noqa: F401 — paddle.nn.ClipGradBy* parity
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)


class ParamAttr:
    """paddle.ParamAttr-lite: carries name/initializer/trainable/
    learning_rate metadata into create_parameter."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
