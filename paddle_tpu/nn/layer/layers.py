"""nn.Layer base class — parity with the reference's
/root/reference/python/paddle/nn/layer/layers.py:334 (params/buffers/
sublayers/hooks/state_dict), re-imagined so a Layer doubles as a pytree of
parameters for the functional jit path (see paddle_tpu.jit.functional_call).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from ...framework import dtype as dtypes
from ...framework.core import Parameter, Tensor, no_grad
from .. import initializer as I

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._id = hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    """Base building block. Holds Parameters, buffers, and sub-layers;
    ``forward`` defines computation over (possibly traced) Tensors."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute routing --------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self._buffers.pop(name, None)
            self._sub_layers.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            object.__setattr__(self, name, value)
        else:
            if name in getattr(self, "_parameters", {}):
                if value is None:
                    del self._parameters[name]
                elif isinstance(value, Tensor):
                    self._parameters[name].set_value(value)
                    return
            if name in getattr(self, "_buffers", {}):
                if isinstance(value, Tensor):
                    self._buffers[name] = value
                    object.__setattr__(self, name, value)
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        for d in ("_parameters", "_buffers", "_sub_layers"):
            dd = self.__dict__.get(d)
            if dd and name in dd:
                return dd[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        self._parameters.pop(name, None)
        self._buffers.pop(name, None)
        self._sub_layers.pop(name, None)
        if name in self.__dict__:
            object.__delattr__(self, name)

    # -- construction helpers ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        dtype = dtype or self._dtype
        init = default_initializer
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        arr = init(tuple(shape), dtype)
        name = getattr(attr, "name", None) if attr is not None else None
        p = Parameter(arr, trainable=not (attr is not None and
                                          getattr(attr, "trainable", True) is False),
                      name=name or "")
        return p

    def create_tensor(self, attr=None, dtype=None, is_bias=False):
        import jax.numpy as jnp
        dtype = dtype or self._dtype
        return Tensor(jnp.zeros((), dtypes.convert_dtype(dtype)))

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        setattr(self, name, sublayer)
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        if tensor is not None:
            tensor.persistable = persistable
        object.__setattr__(self, name, tensor)
        return tensor

    # -- iteration ----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = (prefix + "." + lname) if prefix else lname
                for n, p in layer.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + name if not prefix else prefix + "." + name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = (prefix + "." + lname) if prefix else lname
                yield from layer.named_buffers(prefix=sub_prefix)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = (prefix + "." + name) if prefix else name
            yield sub_prefix, l
            yield from l.named_sublayers(prefix=sub_prefix)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- train/eval ---------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "",
                   use_hook: bool = True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            if self._buffer_persistable(name):
                dest[structured_name_prefix + name] = b
        return dest

    def _buffer_persistable(self, qual_name: str) -> bool:
        parts = qual_name.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return True
        return parts[-1] not in layer._non_persistable_buffer_names

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            arr = v._value if isinstance(v, Tensor) else np.asarray(v)
            target.set_value(arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtypes.convert_dtype(dtype)
            with no_grad():
                for p in self.parameters():
                    if dtypes.is_floating_point(p.dtype):
                        p._replace(p._value.astype(d))
                for b in self.buffers():
                    if b is not None and dtypes.is_floating_point(b.dtype):
                        b._replace(b._value.astype(d))
            self._dtype = d
        if device is not None:
            from ...framework.core import _resolve_device
            dev = _resolve_device(device) if isinstance(device, str) else device
            for p in self.parameters():
                p._replace(jax.device_put(p._value, dev))
            for b in self.buffers():
                if b is not None:
                    b._replace(jax.device_put(b._value, dev))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self._sub_layers.items():
            child_repr = repr(child)
            child_repr = "\n  ".join(child_repr.split("\n"))
            lines.append(f"({name}): {child_repr}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
