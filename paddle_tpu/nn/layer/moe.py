"""MoELayer (parity:
/root/reference/python/paddle/incubate/distributed/models/moe/moe_layer.py:263
plus gates gshard/switch/naive). Expert parallelism = sharding the expert
dim of the dispatched batch over the 'ep' (or 'mp') mesh axis — GSPMD
emits the token all-to-all the reference does manually with
global_scatter/global_gather."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["MoELayer", "SwitchGate", "GShardGate"]


class _GateBase:
    top_k = 2


class GShardGate(_GateBase):
    def __init__(self, top_k=2):
        self.top_k = top_k


class SwitchGate(_GateBase):
    top_k = 1


class MoELayer(Layer):
    """Token-routed expert FFN block.

    Args mirror the reference MoELayer where sensible; experts are the
    standard gated FFN (w1/w2), stored stacked [E, ...] so the expert dim
    can shard over the mesh.
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate="gshard", top_k: int = 2,
                 capacity_factor: float = 1.25, activation="gelu",
                 ep_axis: str = "ep", name=None,
                 dispatch_mode: str = "dense"):
        super().__init__()
        if dispatch_mode not in ("dense", "ragged"):
            raise ValueError(
                f"dispatch_mode must be 'dense' (GShard one-hot, "
                f"EP-shardable) or 'ragged' (sort-based dropless, the "
                f"large-E on-chip path); got {dispatch_mode!r}")
        self.dispatch_mode = dispatch_mode
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        if isinstance(gate, SwitchGate):
            self.top_k = 1
        elif isinstance(gate, _GateBase):
            self.top_k = gate.top_k
        elif gate == "switch":
            self.top_k = 1
        else:
            self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.ep_axis = ep_axis
        self._act_name = activation
        self.gate_weight = self.create_parameter(
            (d_model, num_experts), default_initializer=I.XavierUniform())
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=I.XavierUniform())
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierUniform())
        self._aux_loss: Optional[Tensor] = None
        self._annotate_ep()

    def _annotate_ep(self):
        """Shard expert-stacked params over the ep axis when a fleet mesh
        with that axis exists."""
        from ...distributed.fleet import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            self._mesh = None
            return
        mesh = hcg.mesh
        if self.ep_axis not in mesh.dim_names or \
                mesh.get_dim_size(self.ep_axis) <= 1:
            # fall back to the mp axis for expert sharding
            self.ep_axis = "mp" if mesh.get_dim_size("mp") > 1 else None
        self._mesh = mesh
        if self.ep_axis is None:
            return
        from ...distributed.placement import Replicate, Shard
        from ...distributed.fleet.mpu import _annotate_param
        for p in (self.w1, self.w2):
            _annotate_param(p, mesh, 0, self.ep_axis)

    def _ep_sharding(self):
        if self._mesh is None or self.ep_axis is None:
            return None
        spec = [self.ep_axis, None, None]
        return jax.sharding.NamedSharding(
            self._mesh.to_jax_mesh(), jax.sharding.PartitionSpec(*spec))

    def forward(self, x):
        from ...ops.moe import moe_dispatch_combine, moe_ragged_forward
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self._act_name]
        ep_sharding = self._ep_sharding()
        ragged = self.dispatch_mode == "ragged"
        if ragged and ep_sharding is not None:
            raise NotImplementedError(
                "dispatch_mode='ragged' cannot shard over an expert-"
                "parallel mesh axis (segment sizes are data-dependent); "
                "use dispatch_mode='dense' under EP")

        def f(xa, gw, w1, w2):
            if ragged:
                out, aux, stats = moe_ragged_forward(
                    xa, gw, w1, w2, self.top_k, act)
                cap = jnp.float32(0.0)       # dropless: no capacity
            else:
                out, aux, stats = moe_dispatch_combine(
                    xa, gw, w1, w2, self.top_k, self.capacity_factor,
                    act, ep_sharding)
                cap = stats["capacity"]
            return (out, aux, stats["tokens_per_expert"],
                    stats["assigned_per_expert"],
                    stats["dropped_fraction"], cap)

        out, aux, routed, assigned, dropped, cap = apply(
            "moe", f, x, self.gate_weight, self.w1, self.w2)
        self._aux_loss = aux
        if isinstance(routed._value, jax.core.Tracer):
            # inside a compiled program the stats are traced values that
            # must not leak out of the trace; None (not stale numbers)
            self._last_stats = None
        else:
            self._last_stats = {
                "tokens_per_expert": routed,
                "assigned_per_expert": assigned,
                "dropped_fraction": dropped,
                "capacity": cap,
            }
        return out

    @property
    def aux_loss(self) -> Optional[Tensor]:
        """Load-balancing loss of the last forward (add to the train loss)."""
        return self._aux_loss

    @property
    def routing_stats(self) -> Optional[dict]:
        """Expert-utilization / capacity-overflow diagnostics of the last
        EAGER forward (reference surfaces these through the moe utils
        counters): tokens_per_expert, assigned_per_expert,
        dropped_fraction, capacity — Tensors, fetch with .numpy().
        None when the last forward ran inside a compiled program (run
        one eager forward to sample routing)."""
        return getattr(self, "_last_stats", None)
