"""Recurrent layers (parity:
/root/reference/python/paddle/nn/layer/rnn.py — RNNCellBase,
SimpleRNNCell/LSTMCell/GRUCell, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU
multi-layer networks).

TPU-native: the time loop is ONE jax.lax.scan per layer/direction — the
whole sequence compiles to a single fused XLA while-op; the per-step
matmuls batch over [batch, hidden] (MXU-shaped), and input projections
for all timesteps are hoisted out of the scan (x @ W_ih computed as one
big [B*T, H] matmul). sequence_length masking carries the pre-step state
through padded steps, matching the reference's variable-length
semantics.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor, apply, default_generator
from ...framework import dtype as dtypes
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _uniform(shape, bound, dtype=jnp.float32):
    k = default_generator.next_key()
    return jax.random.uniform(k, shape, dtype, -bound, bound)


class RNNCellBase(Layer):
    """Base cell (reference RNNCellBase): single-step state transition
    with get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value: float = 0.0, batch_dim_idx: int = 0):
        b = batch_ref.shape[batch_dim_idx]
        n = self.state_shape
        if isinstance(n, (tuple, list)) and isinstance(n[0], (tuple, list)):
            return tuple(
                Tensor(jnp.full((b,) + tuple(s), init_value, jnp.float32))
                for s in n)
        return Tensor(jnp.full((b,) + tuple(n), init_value, jnp.float32))


class SimpleRNNCell(RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = Parameter(_uniform((hidden_size, input_size), std))
        self.weight_hh = Parameter(_uniform((hidden_size, hidden_size), std))
        self.bias_ih = Parameter(_uniform((hidden_size,), std))
        self.bias_hh = Parameter(_uniform((hidden_size,), std))

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, pre_x, h, wih, whh, bih, bhh):
        """pre_x: x @ wih.T + bih, already hoisted."""
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        return act(pre_x + h @ whh.T + bhh)

    def _gate_params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def f(x, h, wih, whh, bih, bhh):
            return self._step(x @ wih.T + bih, h, wih, whh, bih, bhh)
        h = apply("simple_rnn_cell", f, inputs, states,
                  *self._gate_params())
        return h, h


class LSTMCell(RNNCellBase):
    """Standard LSTM step (gates i, f, g, o in paddle's order)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=None, name=None):
        super().__init__()
        if proj_size:
            raise NotImplementedError(
                "LSTMCell proj_size (projected LSTM) is not implemented; "
                "silently ignoring it would compute a different model")
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = Parameter(
            _uniform((4 * hidden_size, input_size), std))
        self.weight_hh = Parameter(
            _uniform((4 * hidden_size, hidden_size), std))
        self.bias_ih = Parameter(_uniform((4 * hidden_size,), std))
        self.bias_hh = Parameter(_uniform((4 * hidden_size,), std))

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def _gate_params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    def _step(self, pre_x, hc, wih, whh, bih, bhh):
        h, c = hc
        gates = pre_x + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return h2, c2

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def f(x, h, c, wih, whh, bih, bhh):
            h2, c2 = self._step(x @ wih.T + bih, (h, c), wih, whh, bih,
                                bhh)
            return h2, c2
        h, c = apply("lstm_cell", f, inputs, states[0], states[1],
                     *self._gate_params())
        return h, (h, c)


class GRUCell(RNNCellBase):
    """GRU step (gates r, z, c in paddle's layout)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = Parameter(
            _uniform((3 * hidden_size, input_size), std))
        self.weight_hh = Parameter(
            _uniform((3 * hidden_size, hidden_size), std))
        self.bias_ih = Parameter(_uniform((3 * hidden_size,), std))
        self.bias_hh = Parameter(_uniform((3 * hidden_size,), std))

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _gate_params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    def _step(self, pre_x, h, wih, whh, bih, bhh):
        xr, xz, xc = jnp.split(pre_x, 3, axis=-1)
        hr, hz, hc = jnp.split(h @ whh.T + bhh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        return (1 - z) * c + z * h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def f(x, h, wih, whh, bih, bhh):
            return self._step(x @ wih.T + bih, h, wih, whh, bih, bhh)
        h = apply("gru_cell", f, inputs, states, *self._gate_params())
        return h, h


def _scan_layer(cell, xs, init_states, wih, whh, bih, bhh,
                seq_lens=None, reverse=False):
    """One direction of one layer as a lax.scan. xs: [B, T, I] arrays.
    Returns (outputs [B, T, H], final_states)."""
    b, t_len = xs.shape[0], xs.shape[1]
    # hoist the input projection: one big MXU matmul for all steps
    pre = (xs.reshape(b * t_len, -1) @ wih.T + bih).reshape(
        b, t_len, -1).transpose(1, 0, 2)  # [T, B, 4H?]
    if reverse:
        pre = pre[::-1]

    is_lstm = isinstance(init_states, tuple)

    def step(carry, inp):
        pre_x, t = inp
        new = cell._step(pre_x, carry, wih, whh, bih, bhh)
        if seq_lens is not None:
            # padded steps carry the previous state through
            tt = (t_len - 1 - t) if reverse else t
            active = (tt < seq_lens)[:, None]
            if is_lstm:
                new = (jnp.where(active, new[0], carry[0]),
                       jnp.where(active, new[1], carry[1]))
            else:
                new = jnp.where(active, new, carry)
        out = new[0] if is_lstm else new
        if seq_lens is not None:
            # outputs at padded steps are zero (reference semantics)
            out = jnp.where(active, out, jnp.zeros_like(out))
        return new, out

    ts = jnp.arange(t_len)
    final, outs = jax.lax.scan(step, init_states, (pre, ts))
    outs = outs.transpose(1, 0, 2)
    if reverse:
        outs = outs[:, ::-1]
    return outs, final


class RNN(Layer):
    """Wraps a cell into a full-sequence layer (reference RNN)."""

    def __init__(self, cell, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self.cell
        if initial_states is None:
            ref = inputs if not self.time_major else \
                inputs.transpose([1, 0, 2])
            initial_states = cell.get_initial_states(ref)
        is_lstm = isinstance(initial_states, (tuple, list))

        def f(xs, *arrs):
            it = iter(arrs)
            if is_lstm:
                st = (next(it), next(it))
            else:
                st = next(it)
            wih, whh, bih, bhh = next(it), next(it), next(it), next(it)
            lens = next(it) if sequence_length is not None else None
            if self.time_major:
                xs = xs.transpose(1, 0, 2)
            outs, final = _scan_layer(cell, xs, st, wih, whh, bih, bhh,
                                      lens, self.is_reverse)
            if self.time_major:
                outs = outs.transpose(1, 0, 2)
            if is_lstm:
                return outs, final[0], final[1]
            return outs, final

        states = list(initial_states) if is_lstm else [initial_states]
        args = [inputs, *states, *cell._gate_params()]
        if sequence_length is not None:
            args.append(sequence_length)
        res = apply("rnn", f, *args)
        if is_lstm:
            return res[0], (res[1], res[2])
        return res


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major: bool = False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        out_f, st_f = self.rnn_fw(inputs, sf, sequence_length)
        out_b, st_b = self.rnn_bw(inputs, sb, sequence_length)
        from ...tensor.manipulation import concat
        return concat([out_f, out_b], axis=-1), (st_f, st_b)


class _MultiLayerRNN(Layer):
    """Shared machinery of SimpleRNN / LSTM / GRU (reference rnn.py
    RNNBase): num_layers stacked, optional bidirection, inter-layer
    dropout."""

    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.is_reverse_single = direction == "backward"
        kw = {}
        if activation is not None and self.CELL is SimpleRNNCell:
            kw["activation"] = activation
        from .container import LayerList
        self._cells = LayerList()
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * num_dir
            for _ in range(num_dir):
                self._cells.append(self.CELL(in_sz, hidden_size, **kw))

    @property
    def _num_dir(self):
        return 2 if self.bidirectional else 1

    def _state_slice(self, initial_states, idx):
        """Slice layer*dir entry `idx` out of reference-layout initial
        states ([num_layers*num_dir, B, H], or an (h, c) pair for
        LSTM)."""
        if initial_states is None:
            return None
        if isinstance(initial_states, (tuple, list)):
            return tuple(s[idx] for s in initial_states)
        return initial_states[idx]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...nn import functional as F
        x = inputs
        finals = []
        nd = self._num_dir
        for layer in range(self.num_layers):
            if self.bidirectional:
                cf = self._cells[layer * nd]
                cb = self._cells[layer * nd + 1]
                bi = BiRNN(cf, cb, time_major=self.time_major)
                init = None
                if initial_states is not None:
                    init = (self._state_slice(initial_states, layer * nd),
                            self._state_slice(initial_states,
                                              layer * nd + 1))
                x, (sf, sb) = bi(x, init, sequence_length)
                finals.extend([sf, sb])
            else:
                cell = self._cells[layer]
                rnn = RNN(cell, is_reverse=self.is_reverse_single,
                          time_major=self.time_major)
                x, st = rnn(x, self._state_slice(initial_states, layer),
                            sequence_length)
                finals.append(st)
            if self.dropout and layer < self.num_layers - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)

        from ...tensor.manipulation import stack
        if isinstance(finals[0], tuple):  # LSTM: (h, c) per layer*dir
            h = stack([f[0] for f in finals], axis=0)
            c = stack([f[1] for f in finals], axis=0)
            return x, (h, c)
        return x, stack(finals, axis=0)


class SimpleRNN(_MultiLayerRNN):
    CELL = SimpleRNNCell


class LSTM(_MultiLayerRNN):
    CELL = LSTMCell


class GRU(_MultiLayerRNN):
    CELL = GRUCell
