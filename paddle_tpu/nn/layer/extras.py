"""Remaining layer/loss parity (reference python/paddle/nn/layer/):
ZeroPad2D, Unflatten, Softmax2D, PairwiseDistance, MaxUnPool1/2/3D,
CTCLoss (lax.scan forward algorithm), GaussianNLLLoss, SoftMarginLoss,
MultiLabelSoftMarginLoss, MultiMarginLoss,
TripletMarginWithDistanceLoss, HSigmoidLoss."""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor, apply, default_generator
from .layers import Layer

__all__ = ["ZeroPad2D", "Unflatten", "Softmax2D", "PairwiseDistance",
           "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D", "CTCLoss", "RNNTLoss",
           "FractionalMaxPool2D", "FractionalMaxPool3D",
           "GaussianNLLLoss", "SoftMarginLoss", "MultiLabelSoftMarginLoss",
           "MultiMarginLoss", "TripletMarginWithDistanceLoss",
           "HSigmoidLoss"]


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
        self.padding = list(p)  # [left, right, top, bottom]
        self.data_format = data_format

    def forward(self, x):
        l, r, t, b = self.padding
        if self.data_format == "NCHW":
            pads = ((0, 0), (0, 0), (t, b), (l, r))
        else:
            pads = ((0, 0), (t, b), (l, r), (0, 0))
        return apply("zero_pad2d", lambda a: jnp.pad(a, pads), x)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = list(shape)

    def forward(self, x):
        from ...tensor.extras import unflatten
        return unflatten(x, self.axis, self.shape)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW (reference Softmax2D)."""

    def forward(self, x):
        return apply("softmax2d", lambda a: jax.nn.softmax(a, axis=-3), x)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        def f(a, b):
            d = a - b + self.epsilon
            return jnp.linalg.norm(d, ord=self.p, axis=-1,
                                   keepdims=self.keepdim)
        return apply("pairwise_distance", f, x, y)


class _MaxUnPoolND(Layer):
    """Scatter pooled values back to pre-pool positions using the
    indices MaxPool returned (reference MaxUnPool1D/2D/3D)."""

    ND = 2

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        nd = self.ND
        as_t = lambda v: tuple(v) if isinstance(v, (list, tuple)) \
            else (v,) * nd
        self.kernel = as_t(kernel_size)
        self.stride = as_t(stride if stride is not None else kernel_size)
        self.padding = as_t(padding)
        self.output_size = output_size

    def _out_spatial(self, in_spatial):
        if self.output_size is not None:
            return tuple(self.output_size[-self.ND:])
        return tuple((s - 1) * st - 2 * p + k for s, st, p, k in
                     zip(in_spatial, self.stride, self.padding,
                         self.kernel))

    def forward(self, x, indices):
        nd = self.ND

        def f(a, idx):
            b, c = a.shape[0], a.shape[1]
            out_sp = self._out_spatial(a.shape[2:])
            flat_len = int(jnp.prod(jnp.asarray(out_sp)))
            flat = jnp.zeros((b, c, flat_len), a.dtype)
            vals = a.reshape(b, c, -1)
            ids = idx.reshape(b, c, -1).astype(jnp.int32)
            bi = jnp.arange(b)[:, None, None]
            ci = jnp.arange(c)[None, :, None]
            flat = flat.at[bi, ci, ids].set(vals)
            return flat.reshape((b, c) + tuple(out_sp))

        return apply("max_unpool", f, x, indices)


class MaxUnPool1D(_MaxUnPoolND):
    ND = 1


class MaxUnPool2D(_MaxUnPoolND):
    ND = 2


class MaxUnPool3D(_MaxUnPoolND):
    ND = 3


class CTCLoss(Layer):
    """Connectionist temporal classification (reference CTCLoss over
    warpctc). TPU-native: the alpha recursion of the CTC forward
    algorithm as one lax.scan over time in log space — differentiable,
    so the gradient is exact (autodiff of the forward algorithm)."""

    def __init__(self, blank: int = 0, reduction: str = "mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        """log_probs: [T, B, C] (logits accepted — re-normalized);
        labels: [B, L]; lengths: [B]. Delegates to the canonical
        functional (nn.functional.ctc_loss)."""
        from ..functional.loss import ctc_loss as _ctc
        return _ctc(log_probs, labels, input_lengths, label_lengths,
                    blank=self.blank, reduction=self.reduction,
                    norm_by_times=norm_by_times)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        def f(mu, y, var):
            var = jnp.maximum(var, self.epsilon)
            loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
            if self.full:
                loss = loss + 0.5 * math.log(2 * math.pi)
            return loss
        out = apply("gaussian_nll", f, input, label, variance)
        if self.reduction == "mean":
            return out.mean()
        if self.reduction == "sum":
            return out.sum()
        return out


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        out = apply("soft_margin",
                    lambda x, y: jnp.log1p(jnp.exp(-y * x)), input, label)
        if self.reduction == "mean":
            return out.mean()
        if self.reduction == "sum":
            return out.sum()
        return out


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        def f(x, y, *w):
            loss = -(y * jax.nn.log_sigmoid(x)
                     + (1 - y) * jax.nn.log_sigmoid(-x))
            if w:
                loss = loss * w[0]
            return loss.mean(axis=-1)
        args = (input, label) + ((self.weight,)
                                 if self.weight is not None else ())
        out = apply("multilabel_soft_margin", f, *args)
        if self.reduction == "mean":
            return out.mean()
        if self.reduction == "sum":
            return out.sum()
        return out


class MultiMarginLoss(Layer):
    def __init__(self, p: int = 1, margin: float = 1.0, weight=None,
                 reduction="mean", name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        def f(x, y, *w):
            n, c = x.shape
            correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32),
                                          axis=1)
            m = jnp.maximum(0.0, self.margin - correct + x) ** self.p
            if w:
                m = m * jnp.take(w[0], y.astype(jnp.int32))[:, None]
            mask = jax.nn.one_hot(y.astype(jnp.int32), c) == 0
            return (m * mask).sum(axis=1) / c
        args = (input, label) + ((self.weight,)
                                 if self.weight is not None else ())
        out = apply("multi_margin", f, *args)
        if self.reduction == "mean":
            return out.mean()
        if self.reduction == "sum":
            return out.sum()
        return out


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function: Optional[Callable] = None,
                 margin: float = 1.0, swap: bool = False,
                 reduction: str = "mean", name=None):
        super().__init__()
        self.dist = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        if self.dist is not None:
            d_ap = self.dist(input, positive)
            d_an = self.dist(input, negative)
            if self.swap:
                d_pn = self.dist(positive, negative)
                from ...tensor.math import minimum
                d_an = minimum(d_an, d_pn)
            from ...tensor.math import maximum
            from ...framework.core import Tensor as _T
            import numpy as _np
            zero = Tensor(jnp.zeros_like(d_ap._value))
            out = maximum(d_ap - d_an + self.margin, zero)
        else:
            def f(a, p, n):
                d_ap = jnp.linalg.norm(a - p, axis=-1)
                d_an = jnp.linalg.norm(a - n, axis=-1)
                if self.swap:
                    d_pn = jnp.linalg.norm(p - n, axis=-1)
                    d_an = jnp.minimum(d_an, d_pn)
                return jnp.maximum(d_ap - d_an + self.margin, 0.0)
            out = apply("triplet_margin_dist", f, input, positive,
                        negative)
        if self.reduction == "mean":
            return out.mean()
        if self.reduction == "sum":
            return out.sum()
        return out


import functools


@functools.lru_cache(maxsize=32)
def _hsigmoid_tree_tables(num_classes: int):
    """Complete-binary-tree (path_table, path_code, valid) arrays —
    shared by the HSigmoidLoss layer and F.hsigmoid_loss, cached since
    they depend only on num_classes."""
    import numpy as np
    C = num_classes
    depth = max(1, math.ceil(math.log2(max(C, 2))))
    table = np.zeros((C, depth), np.int32)
    code = np.zeros((C, depth), np.float32)
    valid = np.zeros((C, depth), np.float32)
    for cls in range(C):
        node = cls + C - 1  # leaf id in heap order
        path = []
        while node > 0:
            parent = (node - 1) // 2
            path.append((parent, float(node == 2 * parent + 2)))
            node = parent
        for dpt, (p, bit) in enumerate(reversed(path)):
            table[cls, dpt] = p
            code[cls, dpt] = bit
            valid[cls, dpt] = 1.0
    return table, code, valid


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over a default complete binary tree
    (reference HSigmoidLoss without custom paths: feature_size →
    num_classes via log2(C) binary decisions)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "custom tree paths: pass path_table/path_code to forward")
        self.num_classes = num_classes
        d = feature_size
        n_inner = num_classes - 1  # inner nodes of the complete tree
        std = 1.0 / math.sqrt(d)
        k = default_generator.next_key()
        self.weight = Parameter(
            jax.random.uniform(k, (n_inner, d), jnp.float32, -std, std))
        self.bias = Parameter(jnp.zeros((n_inner,), jnp.float32))
        self._table, self._code, self._valid = \
            _hsigmoid_tree_tables(num_classes)

    def forward(self, input, label, path_table=None, path_code=None):
        table, code, valid = self._table, self._code, self._valid

        def f(x, y, w, b):
            tb = jnp.asarray(table)[y.astype(jnp.int32)]   # [B, D]
            cd = jnp.asarray(code)[y.astype(jnp.int32)]
            vd = jnp.asarray(valid)[y.astype(jnp.int32)]
            wn = w[tb]                                     # [B, D, F]
            bn = b[tb]
            logits = jnp.einsum("bf,bdf->bd", x, wn) + bn
            # bit=1 → sigmoid(logit), bit=0 → 1-sigmoid
            logp = jnp.where(cd > 0.5, jax.nn.log_sigmoid(logits),
                             jax.nn.log_sigmoid(-logits))
            return -(logp * vd).sum(axis=1, keepdims=True)

        return apply("hsigmoid_loss", f, input, label, self.weight,
                     self.bias)


class RNNTLoss(Layer):
    """RNN-T transducer loss layer (reference paddle.nn.RNNTLoss) —
    wraps nn.functional.rnnt_loss (lax.scan alpha recursion)."""

    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        from ..functional.extras import rnnt_loss
        return rnnt_loss(input, label, input_lengths, label_lengths,
                         blank=self.blank,
                         fastemit_lambda=self.fastemit_lambda,
                         reduction=self.reduction)


class _FractionalMaxPoolND(Layer):
    """Fractional max pooling (reference paddle.nn.FractionalMaxPool2D/
    3D): pooling regions from the fractional index sequence
    floor(alpha*(i+u)) with alpha = in/out (pseudo-random u, fixed per
    call via random_u or the global RNG)."""

    ND = 2

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def _edges(self, n_in, n_out, u):
        import numpy as _np
        alpha = n_in / n_out
        idx = _np.floor(alpha * (_np.arange(n_out) + u)).astype(int)
        idx = _np.clip(idx, 0, n_in - 1)
        end = _np.floor(alpha * (_np.arange(1, n_out + 1) + u)) \
            .astype(int)
        end = _np.clip(end, idx + 1, n_in)
        return idx, end

    def forward(self, x):
        import numpy as _np
        from ...framework.core import default_generator
        nd = self.ND
        spatial = x.shape[-nd:]
        out_sz = self.output_size
        if isinstance(out_sz, int):
            out_sz = (out_sz,) * nd
        if self.random_u is not None:
            us = [float(self.random_u)] * nd
        else:
            import jax as _jax
            key = default_generator.next_key()
            us = [float(v) for v in _jax.random.uniform(key, (nd,))]
        # slice-and-reduce per output cell, built as gather of cumulative
        # maxima: simple (loop over output cells host-side — shapes are
        # static and small for pooling layers)
        out = x
        for d in range(nd):
            axis = x.ndim - nd + d
            starts, ends = self._edges(spatial[d], out_sz[d], us[d])
            from ...tensor.manipulation import stack as _stack
            slices = []
            for s0, e0 in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[axis] = slice(int(s0), int(e0))
                piece = out[tuple(sl)]
                slices.append(piece.max(axis=axis))
            out = _stack(slices, axis=axis)
        if self.return_mask:
            return out, None
        return out


class FractionalMaxPool2D(_FractionalMaxPoolND):
    ND = 2


class FractionalMaxPool3D(_FractionalMaxPoolND):
    ND = 3
