"""Activation layers (parity:
/root/reference/python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Silu", "SiLU",
           "Swish", "Sigmoid", "Hardsigmoid", "Hardswish", "Hardtanh",
           "Tanh", "Tanhshrink", "Softshrink", "Hardshrink", "LeakyReLU",
           "PReLU", "RReLU", "Mish", "Softplus", "Softsign", "Softmax",
           "LogSoftmax", "LogSigmoid", "GLU", "Maxout", "ThresholdedReLU"]


def _simple(name, fn_name, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            # map positional args onto default keys in order
            for k, v in zip(defaults.keys(), args):
                merged[k] = v
            for k, v in kwargs.items():
                if k in ("name",):
                    continue
                merged[k] = v
            self._kwargs = merged

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = name
    return _Act


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
ELU = _simple("ELU", "elu", alpha=1.0)
SELU = _simple("SELU", "selu")
CELU = _simple("CELU", "celu", alpha=1.0)
GELU = _simple("GELU", "gelu", approximate=False)
Silu = _simple("Silu", "silu")
SiLU = Silu
Swish = _simple("Swish", "swish")
Sigmoid = _simple("Sigmoid", "sigmoid")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", min=-1.0, max=1.0)
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softshrink = _simple("Softshrink", "softshrink", threshold=0.5)
Hardshrink = _simple("Hardshrink", "hardshrink", threshold=0.5)
LeakyReLU = _simple("LeakyReLU", "leaky_relu", negative_slope=0.01)
Mish = _simple("Mish", "mish")
Softplus = _simple("Softplus", "softplus", beta=1.0, threshold=20.0)
Softsign = _simple("Softsign", "softsign")
Softmax = _simple("Softmax", "softmax", axis=-1)
LogSoftmax = _simple("LogSoftmax", "log_softmax", axis=-1)
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
GLU = _simple("GLU", "glu", axis=-1)
Maxout = _simple("Maxout", "maxout", groups=2, axis=1)
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu",
                          threshold=1.0, value=0.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
