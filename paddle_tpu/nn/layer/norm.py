"""Norm layers (parity: /root/reference/python/paddle/nn/layer/norm.py).
BatchNorm keeps running stats as buffers updated in-place; under the jit
path functional_call reads the updated values back out of the trace."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "RMSNorm", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync falls out of GSPMD when the batch axis is
    sharded (the mean/var reductions become cross-replica psums) — so this
    is BatchNorm; convert_sync_batchnorm is an identity re-wrap.
    Reference: python/paddle/nn/layer/norm.py SyncBatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """TPU-first: the transformer workhorse norm (fused rms_norm parity —
    /root/reference/python/paddle/incubate/nn/functional/fused_rms_norm.py)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 dtype="float32", name=None):
        super().__init__(dtype=dtype)
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization: forward(weight) -> weight / sigma_max,
    sigma estimated by `power_iters` rounds of power iteration with
    persistent u/v buffers (parity:
    /root/reference/python/paddle/nn/layer/norm.py SpectralNorm; GAN
    discriminator regularizer). `axis` is the dim treated as rows when
    the weight is flattened to a matrix."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight_shape = list(weight_shape)
        self.axis = axis
        self.power_iters = int(power_iters)
        self.epsilon = float(epsilon)
        import numpy as _np
        h = self.weight_shape[axis]
        w = int(_np.prod(self.weight_shape)) // h
        from ...framework.core import default_generator
        ku, kv = jax.random.split(default_generator.next_key())
        self.register_buffer(
            "weight_u", Tensor(jax.random.normal(ku, (h,), jnp.float32)))
        self.register_buffer(
            "weight_v", Tensor(jax.random.normal(kv, (w,), jnp.float32)))

    def forward(self, weight):
        axis, eps, iters = self.axis, self.epsilon, self.power_iters

        def f(wt, u, v):
            perm = [axis] + [i for i in range(wt.ndim) if i != axis]
            mat = jnp.transpose(wt, perm).reshape(wt.shape[axis], -1)
            mat32 = mat.astype(jnp.float32)

            def norm(x):
                return x / (jnp.linalg.norm(x) + eps)

            for _ in range(max(iters, 1)):
                v = norm(mat32.T @ u)
                u = norm(mat32 @ v)
            sigma = u @ (mat32 @ v)
            out = (wt.astype(jnp.float32) / sigma).astype(wt.dtype)
            return out, u, v

        out, nu, nv = apply("spectral_norm", f, weight,
                            self.weight_u, self.weight_v)
        self.weight_u._replace(jax.lax.stop_gradient(nu._value))
        self.weight_v._replace(jax.lax.stop_gradient(nv._value))
        return out
