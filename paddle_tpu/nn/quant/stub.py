"""Quantization stub (reference:
/root/reference/python/paddle/nn/quant/stub.py Stub/QuanterStub).

A placeholder sublayer marking where an activation observer should be
inserted for a functional API call; QAT/PTQ conversion replaces it with
the configured quanter. Identity until converted.
"""
from __future__ import annotations

from ..layer.layers import Layer

__all__ = ["Stub", "QuanterStub"]


class Stub(Layer):
    """Marks a quantization insertion point. ``observer`` is a quanter
    layer/factory (or None to use the QuantConfig's global activation
    quanter at conversion time)."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x


class QuanterStub(Layer):
    """Converted form of Stub: wraps the materialized quanter and
    applies it to the input (reference stub.py QuanterStub)."""

    def __init__(self, quanter):
        super().__init__()
        self.quanter = quanter

    def forward(self, x):
        return self.quanter(x) if self.quanter is not None else x
