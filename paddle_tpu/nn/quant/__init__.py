"""paddle.nn.quant — weight-only quantized ops + quantization stubs
(reference: /root/reference/python/paddle/nn/quant/__init__.py exports
Stub, weight_only_linear, llm_int8_linear, weight_quantize,
weight_dequantize)."""
from .quantized_linear import (llm_int8_linear, weight_dequantize,
                               weight_only_linear, weight_quantize)
from .stub import QuanterStub, Stub

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]
