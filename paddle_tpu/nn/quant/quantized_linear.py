"""Weight-only quantized linear ops (reference:
/root/reference/python/paddle/nn/quant/quantized_linear.py —
weight_quantize:39, weight_dequantize:96, weight_only_linear:152,
llm_int8_linear:240).

TPU-native design notes:
- The reference's int8/int4 layouts are CUTLASS tile permutations keyed
  on SM arch; here the layout is plain row-major [out, in] (int4 packs
  two nibbles per int8 along the in-dim) and XLA fuses the dequant into
  the matmul's operand read — the win is HBM traffic (the usual decode
  bottleneck), not a special tensor-core path. `arch` is accepted and
  ignored (no SM tiers on TPU).
- Grouped scales (group_size 64/128) quantize in-dim blocks
  independently: scale shape [out, in/group_size].
- llm_int8_linear implements the LLM.int8() outlier decomposition with
  static shapes: a threshold mask splits activation channels; inlier
  channels run through the int8 weight path, outlier channels matmul the
  dequantized weight in the activation dtype. No dynamic gather — XLA
  sees two fixed-shape matmuls and a select.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor, apply, apply_nodiff

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(
            f"group_size must be -1, 64 or 128, got {group_size}")


def weight_quantize(x, algo: str = "weight_only_int8", arch=None,
                    group_size: int = -1):
    """Quantize a [in, out] float weight; returns (quantized [out, in]
    int8 tensor, float32 scales). Per-channel scales have shape [out];
    grouped scales [out, in/group_size]. int4 packs value pairs along
    the in-dim into one int8 (low nibble = even index)."""
    _check(algo, group_size)
    bits = 4 if algo == "weight_only_int4" else 8
    qmax = float(2 ** (bits - 1) - 1)

    def f(w):
        wt = w.astype(jnp.float32).T          # [out, in]
        o, i = wt.shape
        if group_size == -1:
            absmax = jnp.max(jnp.abs(wt), axis=1, keepdims=True)
            scale = absmax / qmax              # [out, 1]
            q = jnp.round(wt / jnp.maximum(scale, 1e-9))
            scale_out = scale[:, 0]
        else:
            if i % group_size:
                raise ValueError(
                    f"in_features {i} not divisible by group_size "
                    f"{group_size}")
            g = wt.reshape(o, i // group_size, group_size)
            absmax = jnp.max(jnp.abs(g), axis=2, keepdims=True)
            scale = absmax / qmax              # [out, groups, 1]
            q = jnp.round(g / jnp.maximum(scale, 1e-9)).reshape(o, i)
            scale_out = scale[:, :, 0]
        q = jnp.clip(q, -qmax - 1, qmax).astype(jnp.int8)
        if bits == 4:
            if i % 2:
                raise ValueError(
                    f"weight_only_int4 needs even in_features, got {i}")
            lo = q[:, 0::2] & 0x0F
            hi = (q[:, 1::2] & 0x0F) << 4
            q = (lo | hi).astype(jnp.int8)     # [out, in/2]
        return q, scale_out.astype(jnp.float32)

    return apply_nodiff("weight_quantize", f, x)


def _unpack_int4(q):
    """[out, in/2] packed int8 → [out, in] int8 (sign-extended nibbles)."""
    lo = (q << 4).astype(jnp.int8) >> 4        # arithmetic shift extends
    hi = q >> 4                                 # int8 >> is arithmetic
    return jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)


def _dequant(q, scale, algo, group_size, out_dtype):
    w = _unpack_int4(q) if algo == "weight_only_int4" else q
    wf = w.astype(jnp.float32)
    if scale.ndim == 1:
        wf = wf * scale[:, None]
    else:                                       # grouped [out, groups]
        o, i = wf.shape
        wf = (wf.reshape(o, scale.shape[1], -1)
              * scale[:, :, None]).reshape(o, i)
    return wf.astype(out_dtype)


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype="float16", group_size: int = -1):
    """Inverse of weight_quantize: [out, in(/2)] int8 + scales →
    [in, out] float (reference returns the transposition back)."""
    _check(algo, group_size)
    from ...framework import dtype as dtypes
    d = dtypes.convert_dtype(out_dtype)

    def f(q, s):
        return _dequant(q, s, algo, group_size, d).T

    return apply_nodiff("weight_dequantize", f, x, scale)


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """y = x @ dequant(weight).T + bias with int8/int4-stored weight
    [out, in(/2)]. The dequant happens in-trace so XLA fuses it into the
    matmul's weight read — HBM traffic drops 2×/4× vs bf16 weights."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8/int4, "
                         f"got {weight_dtype!r}")
    algo = "weight_only_int4" if weight_dtype == "int4" \
        else "weight_only_int8"
    _check(algo, group_size)
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale "
                         "(output of weight_quantize)")
    args = [x, weight, weight_scale] + ([bias] if bias is not None else [])

    def f(a, q, s, *b):
        w = _dequant(q, s, algo, group_size, a.dtype)   # [out, in]
        y = a @ w.T
        if b:
            y = y + b[0].astype(y.dtype)
        return y

    return apply("weight_only_linear", f, *args)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """LLM.int8() linear: activation channels whose absmax exceeds
    ``threshold`` bypass quantization (matmul the dequantized weight in
    x.dtype); the rest run the int8 weight path. Static-shape form: the
    channel mask selects between the two matmul results — no gather, so
    one compiled program serves every outlier pattern."""
    if weight_scale is None:
        raise ValueError("llm_int8_linear requires weight_scale")
    args = [x, weight, weight_scale] + ([bias] if bias is not None else [])

    def f(a, q, s, *b):
        af = a.astype(jnp.float32)
        # per-channel outlier mask over the in-dim (reduce batch dims)
        red = tuple(range(af.ndim - 1))
        outlier = jnp.max(jnp.abs(af), axis=red) > threshold   # [in]
        w = _dequant(q, s, "weight_only_int8", -1, jnp.float32)  # [o,i]
        a_out = jnp.where(outlier, af, 0.0)
        a_in = jnp.where(outlier, 0.0, af)
        # inlier path: dynamic per-row int8 activations × int8 weight
        row_max = jnp.max(jnp.abs(a_in), axis=-1, keepdims=True)
        a_scale = jnp.maximum(row_max, 1e-9) / 127.0
        a_q = jnp.clip(jnp.round(a_in / a_scale), -128, 127
                       ).astype(jnp.int8)
        acc = jnp.matmul(a_q, q.T.astype(jnp.int8),
                         preferred_element_type=jnp.int32)
        y_in = acc.astype(jnp.float32) * a_scale * s  # s: [out]
        y_out = a_out @ w.T
        y = (y_in + y_out).astype(a.dtype)
        if b:
            y = y + b[0].astype(y.dtype)
        return y

    return apply("llm_int8_linear", f, *args)
