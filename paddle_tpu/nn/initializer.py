"""paddle.nn.initializer parity
(/root/reference/python/paddle/nn/initializer/). Initializers are callables
shape×dtype → jax array drawn from the global Generator key stream."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, default_generator

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    table = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return table.get(nonlinearity, 1.0)


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *k]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        d = dtypes.convert_dtype(dtype)
        return self.mean + self.std * jax.random.normal(k, tuple(shape), d)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        d = dtypes.convert_dtype(dtype)
        return self.mean + self.std * jax.random.truncated_normal(
            k, self.a, self.b, tuple(shape), d)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        d = dtypes.convert_dtype(dtype)
        return jax.random.uniform(k, tuple(shape), d, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = default_generator.next_key()
        return std * jax.random.normal(k, tuple(shape), dtypes.convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = default_generator.next_key()
        return jax.random.uniform(k, tuple(shape), dtypes.convert_dtype(dtype),
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        std = self.gain / math.sqrt(fi)
        k = default_generator.next_key()
        return std * jax.random.normal(k, tuple(shape), dtypes.convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        limit = self.gain * math.sqrt(3.0 / fi)
        k = default_generator.next_key()
        return jax.random.uniform(k, tuple(shape), dtypes.convert_dtype(dtype),
                                  -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtypes.convert_dtype(dtype))
        return arr.reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(
            k, tuple(shape), dtypes.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(tuple(shape), dtype=np.float32)
        oc, ic = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            arr[(i, i % ic) + spatial_center] = 1.0
        return jnp.asarray(arr, dtypes.convert_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv (reference
    paddle.nn.initializer.Bilinear). Weight layout
    [in_c, out_c/groups, kh, kw]."""

    def __call__(self, shape, dtype):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv "
                             f"weight, got shape {list(shape)}")
        kh, kw = shape[2], shape[3]
        import numpy as _np
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * fh - 1 - fh % 2) / (2.0 * fh)
        cw = (2 * fw - 1 - fw % 2) / (2.0 * fw)
        yy, xx = _np.meshgrid(_np.arange(kh), _np.arange(kw),
                              indexing="ij")
        filt = ((1 - _np.abs(yy / fh - ch))
                * (1 - _np.abs(xx / fw - cw))).astype(_np.float32)
        w = _np.zeros(tuple(shape), _np.float32)
        w[:, :] = filt
        return jnp.asarray(w, dtypes.convert_dtype(dtype))


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference paddle.nn.initializer.set_global_initializer: default
    initializers applied by create_parameter when a layer doesn't
    specify its own. Pass None to reset."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init)


__all__ += ["Bilinear", "set_global_initializer"]
