"""paddle.fft parity (reference: /root/reference/python/paddle/fft.py).

Thin Tensor-aware wrappers over jnp.fft — XLA lowers these to the TPU
FFT HLO directly; no custom kernels needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor, as_jnp as _v

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    # paddle uses "backward"/"forward"/"ortho" like numpy
    return norm if norm is not None else "backward"


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.fft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.ifft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.fft2(_v(x), s=s, axes=axes, norm=_norm(norm)))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.ifft2(_v(x), s=s, axes=axes, norm=_norm(norm)))


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.fftn(_v(x), s=s, axes=axes, norm=_norm(norm)))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.ifftn(_v(x), s=s, axes=axes, norm=_norm(norm)))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.rfft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.irfft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.rfft2(_v(x), s=s, axes=axes, norm=_norm(norm)))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(jnp.fft.irfft2(_v(x), s=s, axes=axes, norm=_norm(norm)))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.rfftn(_v(x), s=s, axes=axes, norm=_norm(norm)))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(jnp.fft.irfftn(_v(x), s=s, axes=axes, norm=_norm(norm)))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.hfft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return Tensor(jnp.fft.ihfft(_v(x), n=n, axis=axis, norm=_norm(norm)))


def _nd_via_1d(fn1d, x, s, axes, norm):
    """Hermitian n-d FFT as a 1-d hermitian transform on the last axis
    composed with plain (i)ffts on the rest. Axis order matters:
    hfft takes complex input, so leading complex ffts run first; ihfft
    takes REAL input, so it must run first (producing complex), with the
    remaining axes handled by ifft afterwards."""
    v = _v(x)
    if axes is None:
        axes = tuple(range(v.ndim)) if s is None else \
            tuple(range(v.ndim - len(s), v.ndim))
    if s is None:
        s = [None] * len(axes)
    if fn1d is jnp.fft.hfft:
        for ax, n in zip(axes[:-1], s[:-1]):
            v = jnp.fft.fft(v, n=n, axis=ax, norm=norm)
        return fn1d(v, n=s[-1], axis=axes[-1], norm=norm)
    v = fn1d(v, n=s[-1], axis=axes[-1], norm=norm)
    for ax, n in zip(axes[:-1], s[:-1]):
        v = jnp.fft.ifft(v, n=n, axis=ax, norm=norm)
    return v


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(_nd_via_1d(jnp.fft.hfft, x, s, axes, _norm(norm)))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return Tensor(_nd_via_1d(jnp.fft.ihfft, x, s, axes, _norm(norm)))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(_nd_via_1d(jnp.fft.hfft, x, s, axes, _norm(norm)))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return Tensor(_nd_via_1d(jnp.fft.ihfft, x, s, axes, _norm(norm)))


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework import dtype as dtypes
        out = out.astype(dtypes.convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework import dtype as dtypes
        out = out.astype(dtypes.convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.fftshift(_v(x), axes=axes))


def ifftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.ifftshift(_v(x), axes=axes))
