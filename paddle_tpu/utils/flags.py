"""Typed flag registry with env-var overrides.

TPU-native analog of the reference's gflags clone
(/root/reference/paddle/utils/flags_native.h, PHI_DEFINE_EXPORTED_* macros
in paddle/phi/core/flags.h:155): one python registry, values overridable by
FLAGS_<name> environment variables, settable at runtime via set_flags.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

__all__ = ["define_flag", "get_flags", "set_flags", "FLAGS"]


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    type_: type
    value: Any = None


_registry: Dict[str, _Flag] = {}


def _coerce(type_, raw):
    if type_ is bool:
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return type_(raw)


def define_flag(name: str, default: Any, help: str = ""):
    t = type(default)
    f = _Flag(name, default, help, t)
    env = os.environ.get(f"FLAGS_{name}")
    f.value = _coerce(t, env) if env is not None else default
    _registry[name] = f
    return f


def get_flags(flags=None):
    if flags is None:
        return {k: v.value for k, v in _registry.items()}
    if isinstance(flags, str):
        flags = [flags]
    return {k: _registry[k].value for k in flags}


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        if k not in _registry:
            define_flag(k, v)
        else:
            _registry[k].value = _coerce(_registry[k].type_, v)


class _FlagsProxy:
    def __getattr__(self, name):
        if name in _registry:
            return _registry[name].value
        raise AttributeError(name)


FLAGS = _FlagsProxy()

# Core flags (subset parity with paddle/phi/core/flags.cc)
define_flag("check_nan_inf", False, "check outputs for nan/inf after each op")
define_flag("benchmark", False, "benchmark mode: block_until_ready each op")
define_flag("use_pallas_kernels", True,
            "use handwritten Pallas TPU kernels where available")
