"""Deterministic fault injection for the serving engine.

The robustness analogue of tools/flightcheck: flightcheck proves hazard
classes absent STATICALLY; the chaos monkey proves the engine's
fault-tolerance machinery (ISSUE 4 — deadlines, cancellation,
preemption-with-recompute, bounded retry) actually recovers AT RUNTIME,
by injecting seeded failures at the engine's three fault surfaces:

- allocator OOM: ``PagedKVCache.fault_hook`` fires at the top of every
  ``_take_block`` — BEFORE any pool mutation — raising KVCacheExhausted
  exactly as a genuinely dry pool would. The engine answers with
  admission back-pressure or preemption-with-recompute.
- dispatch faults: ``ServingEngine._device_call`` consults
  ``before_call`` ahead of every jitted dispatch. An injected
  InjectedDispatchError is raised BEFORE the underlying call, so no
  donated buffer is consumed and a retry re-runs the identical program
  (same args, same PRNG key) — recovery is token-identical by
  construction.
- collection faults ("corruption"): the same hook ahead of every
  result fetch. Fetches never consume device buffers, so a retried
  fetch returns the SAME tokens — an injected collect fault models a
  torn/corrupt host read that the retry re-reads.
- latency spikes: a seeded ``time.sleep`` ahead of a call — exercises
  deadline enforcement and the watchdog without failing anything.

Everything is driven by one ``numpy.random.RandomState(seed)``: the
same seed + the same engine behavior reproduces the same schedule, so a
chaos failure is a unit test, not a flake. The monkey never mutates
engine state itself — it only raises/sleeps at the sanctioned hooks.

Usage::

    from paddle_tpu.utils.chaos import ChaosMonkey
    monkey = ChaosMonkey(seed=0, p_dispatch=0.05, p_alloc_oom=0.02)
    monkey.attach(engine)
    while engine.step():
        engine.dec.cache.debug_check()
    monkey.detach(engine)
    print(monkey.counts)

``tools/chaos_serving.py`` wraps this in a full harness: randomized
chaos schedules, per-step invariant checks, and token-identity of every
surviving request against a fault-free run.
"""
from __future__ import annotations

import os
import signal
import time
from collections import Counter
from typing import List, Tuple

import numpy as np

from ..ops.paged_attention import KVCacheExhausted

__all__ = ["ChaosMonkey", "InjectedFault", "InjectedDispatchError",
           "InjectedCollectError", "InjectedTransportError"]


class InjectedFault(RuntimeError):
    """Base of every chaos-injected failure (NOT KVCacheExhausted —
    injected allocator OOM deliberately raises the real exhaustion type
    so the engine cannot tell it from true pressure)."""


class InjectedDispatchError(InjectedFault):
    """Injected ahead of a device dispatch (transient device error)."""


class InjectedCollectError(InjectedFault):
    """Injected ahead of a result fetch (torn/corrupt collection)."""


class InjectedTransportError(InjectedFault):
    """Injected at a ProcTransport RPC boundary (ISSUE 19): raised by
    ``transport_fault`` before a send (dropped request) or after a
    receive (dropped response). The transport's bounded retry treats
    it exactly like a real torn pipe — and because retries re-use the
    message id against the worker's reply cache, a dropped RESPONSE is
    the deterministic exactly-once test: the reply crosses twice, the
    step ran once, the journal extends once."""


class ChaosMonkey:
    """Seeded, deterministic fault injector for one ServingEngine.

    p_alloc_oom:  probability a block take raises KVCacheExhausted
    p_dispatch:   probability a dispatch raises InjectedDispatchError
    p_collect:    probability a fetch raises InjectedCollectError
    p_latency:    probability a call is delayed by latency_s first
    p_rpc_drop:   probability a transport RPC stage (send/recv) raises
                  InjectedTransportError (ISSUE 19 — parent-side hook)
    p_rpc_delay:  probability an RPC stage sleeps latency_s first
    """

    def __init__(self, seed: int = 0, p_alloc_oom: float = 0.0,
                 p_dispatch: float = 0.0, p_collect: float = 0.0,
                 p_latency: float = 0.0, latency_s: float = 0.002,
                 p_rpc_drop: float = 0.0, p_rpc_delay: float = 0.0):
        self.rng = np.random.RandomState(seed)
        self.p_alloc_oom = float(p_alloc_oom)
        self.p_dispatch = float(p_dispatch)
        self.p_collect = float(p_collect)
        self.p_latency = float(p_latency)
        self.latency_s = float(latency_s)
        self.p_rpc_drop = float(p_rpc_drop)
        self.p_rpc_delay = float(p_rpc_delay)
        self.counts: Counter = Counter()
        # (call index, site) of every injection, for post-mortems
        self.log: List[Tuple[int, str]] = []
        self._calls = 0
        # the attached engine (telemetry: injections are emitted into
        # its flight recorder when a tracer is enabled, so every red
        # gate run's export shows exactly which faults were injected
        # when, next to the spans they hit)
        self._engine = None

    # -- wiring -------------------------------------------------------------
    def attach(self, engine) -> "ChaosMonkey":
        """Hook this monkey into `engine` (and its KV pool)."""
        engine.chaos = self
        engine.dec.cache.fault_hook = self._alloc_hook
        self._engine = engine
        return self

    def detach(self, engine):
        if engine.chaos is self:
            engine.chaos = None
        if engine.dec.cache.fault_hook == self._alloc_hook:
            engine.dec.cache.fault_hook = None
        if self._engine is engine:
            self._engine = None

    def _trace_event(self, site: str, **attrs):
        eng = self._engine
        tracer = getattr(eng, "tracer", None) if eng is not None \
            else None
        if tracer is not None:
            tracer.event("injected_fault",
                         pid=getattr(eng, "replica_id", 0),
                         site=site, **attrs)

    def wedge(self):
        """Turn this monkey into a PERSISTENT replica wedge (ISSUE 11):
        from now on EVERY dispatch and every fetch raises — the model
        of a replica whose device/link died outright, as opposed to
        the transient faults the probabilities above inject. The
        attached engine's bounded retry exhausts on every call and
        fails the riding requests; above it, the fleet Router reads
        the exhaustion stream as consecutive strikes, trips its
        circuit breaker, and drains the replica (tools/chaos_serving
        --dp leg). Latency/OOM injection keeps its configured rates —
        a wedged device still answers allocator bookkeeping, which is
        host-side anyway."""
        self.p_dispatch = 1.0
        self.p_collect = 1.0
        self.counts["wedged"] += 1
        self.log.append((self._calls, "wedge"))
        self._trace_event("wedge")
        return self

    def kill(self):
        """SIGKILL the CURRENT process — the hard-death analogue of
        wedge() (ISSUE 19): wedge models a device/link that died while
        the host survives; kill models the host process itself dying
        (OOM killer, segfault). Meant to run INSIDE a ProcTransport
        worker (the transport's ``chaos_kill`` verb / ``inject_kill``)
        — the Router observes pipe EOF + waitpid, wedges the replica,
        drains its journal and respawns. Counts/log/trace are emitted
        best-effort first, but a SIGKILL'd process flushes nothing:
        the parent-side counters are the ones that survive."""
        self.counts["kills"] += 1
        self.log.append((self._calls, "kill"))
        self._trace_event("kill")
        os.kill(os.getpid(), signal.SIGKILL)

    def transport_fault(self, stage: str, verb: str):
        """ProcTransport consults this ahead of every RPC send and
        after every receive (``stage`` is 'send' or 'recv'). Raising
        InjectedTransportError models a dropped request / dropped
        response; the transport's bounded retry + the worker's reply
        cache make recovery exactly-once by construction. A seeded
        delay models a slow pipe without failing anything."""
        self._calls += 1
        self.counts["rpc_stages"] += 1
        if self.p_rpc_delay and \
                self.rng.random_sample() < self.p_rpc_delay:
            self.counts["rpc_delays"] += 1
            self.log.append((self._calls, f"rpc_delay:{stage}:{verb}"))
            time.sleep(self.latency_s)
        if self.p_rpc_drop and \
                self.rng.random_sample() < self.p_rpc_drop:
            self.counts["rpc_drops"] += 1
            self.log.append((self._calls, f"rpc_drop:{stage}:{verb}"))
            self._trace_event("rpc_drop", stage=stage, verb=verb)
            raise InjectedTransportError(
                f"chaos: injected rpc {stage} drop at {verb}")

    # -- injection sites ----------------------------------------------------
    def _alloc_hook(self):
        self._calls += 1
        self.counts["alloc_calls"] += 1
        if self.p_alloc_oom and \
                self.rng.random_sample() < self.p_alloc_oom:
            self.counts["alloc_oom"] += 1
            self.log.append((self._calls, "alloc_oom"))
            self._trace_event("alloc_oom")
            raise KVCacheExhausted("chaos: injected allocator OOM")

    def before_call(self, engine, kind: str):
        """ServingEngine._device_call consults this ahead of every
        dispatch/fetch; `kind` is 'dispatch:*' or 'collect:*'. Raising
        here is always retry-safe: the underlying call has not run, so
        nothing was donated or consumed."""
        self._calls += 1
        self.counts["device_calls"] += 1
        if self.p_latency and \
                self.rng.random_sample() < self.p_latency:
            self.counts["latency_spikes"] += 1
            self.log.append((self._calls, f"latency:{kind}"))
            time.sleep(self.latency_s)
        if kind.startswith("collect"):
            if self.p_collect and \
                    self.rng.random_sample() < self.p_collect:
                self.counts["collect_faults"] += 1
                self.log.append((self._calls, kind))
                self._trace_event("collect_fault", kind=kind)
                raise InjectedCollectError(
                    f"chaos: injected collection fault at {kind}")
        else:
            if self.p_dispatch and \
                    self.rng.random_sample() < self.p_dispatch:
                self.counts["dispatch_faults"] += 1
                self.log.append((self._calls, kind))
                self._trace_event("dispatch_fault", kind=kind)
                raise InjectedDispatchError(
                    f"chaos: injected dispatch fault at {kind}")
