"""Dispatch-count-differencing wall timer for remote-dispatch backends.

A host-blocking fetch through the axon TPU tunnel costs a ~75 ms (±a few
ms) round trip, which drowns millisecond-scale per-step signals. JAX
dispatches are async and pipeline on the device, so timing n1 vs n2
back-to-back dispatches — forcing completion only at the end — pays the
round trip once each, and the difference isolates pure device time.

Completion is forced by a HOST FETCH of one result leaf, not
jax.block_until_ready: the tunnel acknowledges block_until_ready without
draining the execution queue (measured: a 137-GFLOP program "completes"
in 0.04 ms under block_until_ready, 4.2 ms under a fetch), so only a
value actually crossing to the host proves the chain ran.

Shared by bench.py (pipeline microbench) and
distributed.fleet.pipeline.PipelineParallel (store-vs-remat auto-pick).
"""
from __future__ import annotations

import time

__all__ = ["timed_dispatch_diff"]


def timed_dispatch_diff(fn, args, calls=(1, 3), repeats=2,
                        per_call: int = 1) -> float:
    """Seconds per unit of work, with per-call constants cancelled:
    (T(n2 calls) - T(n1 calls)) / ((n2 - n1) * per_call).

    fn(*args) must return a pytree of jax arrays (one leaf is fetched);
    per_call is the number of work units one call performs (e.g. the
    scan length inside fn). The caller is responsible for having
    compiled/warmed fn (the first invocation here blocks once before
    timing, which also absorbs any remaining warm-up)."""
    import jax
    import numpy as np

    def force(out):
        # fetch ONE leaf to the host: the only completion proof the
        # remote tunnel honors (its block_until_ready is a no-op)
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(leaf)

    force(fn(*args))
    n1, n2 = calls
    ts = {}
    for n in (n1, n2):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = None
            for _ in range(n):
                out = fn(*args)
            force(out)
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    return max(ts[n2] - ts[n1], 1e-9) / ((n2 - n1) * per_call)
