"""Serving telemetry: span tracing, a flight-recorder ring buffer with
Perfetto export, and a unified metrics registry (ISSUE 12).

The engine composes six subsystems inside ONE device program per step
(PRs 5-11), so host-side visibility is the scarce resource: everything
interesting happens between two dispatches. This module is the
host-side answer — three small, allocation-light primitives every
serving subsystem shares:

- ``Tracer``: per-request SPANS (queued → admitted → prefill chunk i →
  splice-wait → decode → preempt/recompute → migrate →
  done/aborted/failed, each carrying req_id/tenant/replica attributes)
  and per-step EVENTS (dispatch width bucket / rows / tokens, retry,
  injected fault, breaker strike), held in a bounded FLIGHT-RECORDER
  ring buffer (old records fall off; ``dropped`` counts them) with
  Chrome-trace/Perfetto JSON export (``Tracer.export(path)``). A
  request is ONE async span for its whole life — the trace id
  propagates through preemption-recompute and cross-replica migration
  (``ServingEngine.adopt_request(trace_id=...)``), so a migrated
  request renders as a single continuous span crossing two replica
  process tracks in Perfetto.
- ``MetricsRegistry``: counters / gauges / fixed-bucket histograms.
  The engine/fleet/cache/chaos ``stats()`` dicts publish into it under
  namespaced keys ("engine.preemptions", "fleet.failovers", ...), so
  the registry is the unified cross-subsystem view and the per-call
  dicts are views over the same numbers (parity is pinned by
  tests/test_telemetry.py); span durations and ITL/TTFT/latency
  samples additionally feed fixed-bucket histograms live.
- ``Reservoir``: seeded Algorithm-R uniform sampling — the bound on
  the raw per-token ITL sample aggregation in ServingEngine.stats() /
  Router.stats() (exact below capacity, p50/p99-within-tolerance
  above it).

Overhead contract: ``tracer=None`` (the default everywhere) is a
BITWISE no-op — every hook is behind an ``if tracer is not None``
guard, no PRNG key is drawn, no device call is made, no schedule array
changes. Enabled, the hot path appends small dicts to a deque and
never touches a traced array or forces a host sync (the tracer reads
only host-side scheduler state — flightcheck's FC301 family stays at
zero findings over this module and its call sites); the serving bench
pins the enabled overhead < 5% tok/s on the ragged row
(bench.py serving_trace).

Export format: Chrome Trace Event JSON (the ``traceEvents`` array
form), loadable by Perfetto (ui.perfetto.dev) and chrome://tracing.
Request lifecycles are nestable async events (``ph: "b"/"e"``, matched
on ``cat + id`` across process tracks); per-phase slices are complete
events (``ph: "X"`` with ``ts``/``dur``); per-step events are instants
(``ph: "i"``). Engine events land on ``pid = replica_id``; fleet-level
records (routing, breaker, migration, the request async spans) land on
the dedicated ``FLEET_PID`` track.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from bisect import bisect_right
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Tracer", "MetricsRegistry", "Reservoir", "FLEET_PID",
           "DEFAULT_TIME_BUCKETS_S"]

# the pid Chrome-trace track fleet-level records render on (routing,
# breaker transitions, migration, request async spans); engine records
# use pid = replica_id (0 for a single engine), so the two can never
# collide for any plausible fleet size
FLEET_PID = 1000

# fixed histogram buckets for second-valued observations (ITL, TTFT,
# latency, span durations): roughly log-spaced 0.5 ms .. 60 s
DEFAULT_TIME_BUCKETS_S = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
    1.0, 2.0, 5.0, 10.0, 30.0, 60.0)


class Reservoir:
    """Seeded Algorithm-R reservoir: a bounded uniform sample of an
    unbounded stream. Exact (every sample retained, in order) while the
    stream is <= k items; beyond that each seen item has equal
    probability k/n of being retained, so quantiles stay within
    sampling tolerance while memory is O(k). Deterministic: the same
    seed + the same stream reproduces the same sample (the RNG is
    private — engine PRNG streams are untouched)."""

    def __init__(self, k: int = 4096, seed: int = 0):
        self.k = int(k)
        self._rng = np.random.RandomState(seed)
        self.samples: List[float] = []
        self.n = 0                      # items seen (>= len(samples))

    def append(self, x: float):
        if self.n < self.k:
            self.samples.append(float(x))
        else:
            j = int(self._rng.randint(0, self.n + 1))
            if j < self.k:
                self.samples[j] = float(x)
        self.n += 1

    def extend(self, xs: Sequence[float]):
        for x in xs:
            self.append(x)

    def __len__(self) -> int:
        return len(self.samples)

    def __iter__(self):
        return iter(self.samples)

    @staticmethod
    def merge(parts, k: int = 4096, seed: int = 0) -> List[float]:
        """Combine several (samples, n_seen) parts — Reservoir objects
        or (list, n) tuples — into ONE bounded sample whose composition
        is proportional to each part's true stream size (concatenating
        raw reservoirs would over-weight small streams). Exact
        concatenation when everything fits in k."""
        norm = []
        for p in parts:
            if isinstance(p, Reservoir):
                norm.append((p.samples, p.n))
            else:
                s, n = p
                norm.append((list(s), int(n)))
        norm = [(s, n) for s, n in norm if s]
        total = sum(n for _, n in norm)
        if total <= k:
            return [x for s, _ in norm for x in s]
        rng = np.random.RandomState(seed)
        out: List[float] = []
        for s, n in norm:
            want = max(1, int(round(k * n / total)))
            if want >= len(s):
                out.extend(s)
            else:
                idx = rng.choice(len(s), size=want, replace=False)
                out.extend(s[i] for i in idx)
        return out


class _Histogram:
    """Fixed-bucket histogram: counts[i] = observations <= buckets[i]
    boundary (last slot is the overflow), plus n/sum for means."""

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.n = 0
        self.sum = 0.0

    def observe(self, v: float, n: int = 1):
        self.counts[bisect_right(self.buckets, float(v))] += int(n)
        self.n += int(n)
        self.sum += float(v) * int(n)

    def snapshot(self) -> dict:
        return {"buckets": list(self.buckets),
                "counts": list(self.counts),
                "n": self.n, "sum": self.sum,
                "mean": (self.sum / self.n) if self.n else None}


class MetricsRegistry:
    """Unified counters/gauges/histograms across engine, fleet, cache
    and chaos. Two feeding paths:

    - live: ``inc(name)`` from the tracer's event/span hooks (event
      counts, span-duration histograms) — cheap dict ops;
    - published: ``publish(prefix, stats_dict)`` mirrors a subsystem's
      ``stats()`` dict under namespaced keys (ints -> counters, floats
      -> gauges; None/bool/nested values skipped), making the stats
      dicts views over the registry — ``registry.value("engine.X") ==
      engine.stats()["X"]`` for every numeric key (tested).

    Thread-safety: all dict membership mutations and ``snapshot()``
    take one lock, so a watchdog-thread export can never hit a
    dictionary-changed-during-iteration crash while the engine thread
    records a first-seen event/histogram name. Individual histogram
    ``observe`` calls stay lockless (they mutate an existing object in
    place); a concurrent snapshot may read a histogram mid-update,
    which is tolerable for a post-mortem."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, _Histogram] = {}

    def inc(self, name: str, n: float = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, v: float):
        with self._lock:
            self.gauges[name] = float(v)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS_S
                  ) -> _Histogram:
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.get(name)
                if h is None:
                    h = self.histograms[name] = _Histogram(buckets)
        return h

    def publish(self, prefix: str, stats: dict):
        with self._lock:
            for key, v in stats.items():
                name = f"{prefix}.{key}"
                if v is None:
                    # a stat that went back to None (e.g. percentiles
                    # after clear_finished) must not leave its stale
                    # pre-reset value in the registry/export
                    self.counters.pop(name, None)
                    self.gauges.pop(name, None)
                    continue
                if isinstance(v, bool):
                    continue
                if isinstance(v, (int, np.integer)):
                    self.counters[name] = int(v)
                elif isinstance(v, (float, np.floating)):
                    self.gauges[name] = float(v)

    def value(self, name: str):
        with self._lock:
            if name in self.counters:
                return self.counters[name]
            return self.gauges.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "histograms": {k: h.snapshot()
                                   for k, h in self.histograms.items()}}


class Tracer:
    """Flight recorder + span tracer. See the module docstring for the
    taxonomy; the record stream is a bounded deque of small dicts:

    - ``{"kind": "begin"/"end", "name": "request", "trace": id, ...}``
      — request lifecycle (async span endpoints);
    - ``{"kind": "span", "name": phase, "trace": id, "ts": t0,
      "dur": seconds, ...}`` — one completed per-life phase;
    - ``{"kind": "event", "name": ..., ...}`` — per-step instants.

    Timestamps are ``time.perf_counter()`` values (the engine's own
    clock); export rebases them to microseconds from the tracer's
    construction. Thread-safe (the watchdog thread reads ``summary()``
    while the engine appends)."""

    DEFAULT_CAPACITY = 1 << 16

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 metrics: Optional[MetricsRegistry] = None):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.appended = 0
        self.metrics = metrics or MetricsRegistry()
        self._ids = itertools.count(1)
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------------
    def _record(self, rec: dict):
        with self._lock:
            self._ring.append(rec)
            self.appended += 1

    @property
    def dropped(self) -> int:
        """Records that fell off the ring (flight-recorder semantics:
        the newest ``capacity`` records always survive)."""
        return self.appended - len(self._ring)

    def begin_request(self, req_id: int, tenant=None, replica: int = 0,
                      **attrs) -> int:
        """Open one request-lifetime async span; returns its trace id
        (propagate it through adopt_request so a migrated request stays
        ONE span)."""
        tid = next(self._ids)
        args = {"req_id": int(req_id), "replica": int(replica)}
        if tenant is not None:
            args["tenant"] = str(tenant)
        args.update(attrs)
        self._record({"kind": "begin", "name": "request", "trace": tid,
                      "pid": FLEET_PID, "ts": time.perf_counter(),
                      "args": args})
        self.metrics.inc("trace.requests")
        return tid

    def end_request(self, trace_id: Optional[int], state: str,
                    replica: int = 0, **attrs):
        if trace_id is None:
            return
        args = {"state": state, "replica": int(replica)}
        args.update(attrs)
        self._record({"kind": "end", "name": "request",
                      "trace": int(trace_id), "pid": FLEET_PID,
                      "ts": time.perf_counter(), "args": args})
        self.metrics.inc(f"trace.requests_{state}")

    def reopen_request(self, trace_id: Optional[int]) -> bool:
        """Rescind the most recent end record of ``trace_id`` — the
        fleet Router calls this when it migrates a request whose
        fault-burst FAILURE already closed the span (the engine failed
        it before the breaker tripped): the migration supersedes the
        terminal state, so the span must stay open until the adopted
        continuation ends it (one continuous span across replicas).
        Returns False when no end record is in the ring (it either
        never existed or already fell off)."""
        if trace_id is None:
            return False
        with self._lock:
            for r in reversed(self._ring):
                if r["kind"] == "end" and r["trace"] == trace_id:
                    self._ring.remove(r)
                    self.appended -= 1
                    state = r["args"].get("state")
                    if state:
                        self.metrics.inc(f"trace.requests_{state}", -1)
                    return True
        return False

    def span(self, name: str, trace_id: Optional[int], t0: float,
             t1: float, pid: int = 0, **attrs):
        """One completed per-life phase slice [t0, t1] (perf_counter
        seconds) on the replica track ``pid``."""
        self._record({"kind": "span", "name": name,
                      "trace": (int(trace_id) if trace_id is not None
                                else None),
                      "pid": int(pid), "ts": float(t0),
                      "dur": max(0.0, float(t1) - float(t0)),
                      "args": attrs})
        self.metrics.inc(f"spans.{name}")
        self.metrics.histogram(f"span.{name}_s").observe(
            max(0.0, float(t1) - float(t0)))

    def event(self, name: str, trace: Optional[int] = None,
              pid: int = 0, **attrs):
        """One per-step instant (dispatch, retry, injected fault,
        breaker strike, kv alloc/evict/splice/rollback, ...)."""
        self._record({"kind": "event", "name": name,
                      "trace": (int(trace) if trace is not None
                                else None),
                      "pid": int(pid), "ts": time.perf_counter(),
                      "args": attrs})
        self.metrics.inc(f"events.{name}")

    # -- reading -------------------------------------------------------------
    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def summary(self, last: int = 25) -> str:
        """Human-readable tail of the flight recorder (the watchdog
        appends this to its hang report)."""
        recs = self.records()
        lines = [f"flight recorder: {self.appended} records "
                 f"({self.dropped} dropped, capacity {self.capacity}); "
                 f"last {min(last, len(recs))}:"]
        for r in recs[-last:]:
            t = r["ts"] - self._t0
            extra = f" dur={r['dur'] * 1e3:.2f}ms" if "dur" in r else ""
            tidp = f" trace={r['trace']}" if r.get("trace") else ""
            lines.append(f"  +{t:9.3f}s [{r['kind']}] {r['name']}"
                         f"{tidp} pid={r['pid']}{extra} {r['args']}")
        return "\n".join(lines) + "\n"

    # -- export --------------------------------------------------------------
    def _us(self, t: float) -> float:
        return max(0.0, (t - self._t0) * 1e6)

    def export(self, path: str) -> str:
        """Write the flight recorder as Chrome-trace / Perfetto JSON
        (plus the metrics-registry snapshot under ``"metrics"``).
        Returns ``path``."""
        recs = self.records()
        evts: List[dict] = []
        pids = sorted({r["pid"] for r in recs})
        for pid in pids:
            name = ("fleet" if pid == FLEET_PID
                    else f"replica{pid}")
            evts.append({"ph": "M", "name": "process_name", "pid": pid,
                         "tid": 0, "ts": 0,
                         "args": {"name": name}})
        for r in recs:
            tid = r["trace"] if r.get("trace") is not None else 0
            if r["kind"] == "begin":
                evts.append({"ph": "b", "cat": "request",
                             "id": str(r["trace"]),
                             "name": f"req{r['args'].get('req_id', '')}",
                             "pid": r["pid"], "tid": tid,
                             "ts": self._us(r["ts"]),
                             "args": r["args"]})
            elif r["kind"] == "end":
                evts.append({"ph": "e", "cat": "request",
                             "id": str(r["trace"]), "name": "request",
                             "pid": r["pid"], "tid": tid,
                             "ts": self._us(r["ts"]),
                             "args": r["args"]})
            elif r["kind"] == "span":
                evts.append({"ph": "X", "cat": "phase",
                             "name": r["name"], "pid": r["pid"],
                             "tid": tid, "ts": self._us(r["ts"]),
                             "dur": r["dur"] * 1e6,
                             "args": r["args"]})
            else:
                evts.append({"ph": "i", "cat": "step",
                             "name": r["name"], "pid": r["pid"],
                             "tid": tid, "ts": self._us(r["ts"]),
                             "s": "t", "args": r["args"]})
        doc = {"traceEvents": evts, "displayTimeUnit": "ms",
               "otherData": {"dropped_records": self.dropped,
                             "appended_records": self.appended},
               "metrics": self.metrics.snapshot()}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
