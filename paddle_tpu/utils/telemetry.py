"""Serving telemetry: span tracing, a flight-recorder ring buffer with
Perfetto export, and a unified metrics registry (ISSUE 12).

The engine composes six subsystems inside ONE device program per step
(PRs 5-11), so host-side visibility is the scarce resource: everything
interesting happens between two dispatches. This module is the
host-side answer — three small, allocation-light primitives every
serving subsystem shares:

- ``Tracer``: per-request SPANS (queued → admitted → prefill chunk i →
  splice-wait → decode → preempt/recompute → migrate →
  done/aborted/failed, each carrying req_id/tenant/replica attributes)
  and per-step EVENTS (dispatch width bucket / rows / tokens, retry,
  injected fault, breaker strike), held in a bounded FLIGHT-RECORDER
  ring buffer (old records fall off; ``dropped`` counts them) with
  Chrome-trace/Perfetto JSON export (``Tracer.export(path)``). A
  request is ONE async span for its whole life — the trace id
  propagates through preemption-recompute and cross-replica migration
  (``ServingEngine.adopt_request(trace_id=...)``), so a migrated
  request renders as a single continuous span crossing two replica
  process tracks in Perfetto.
- ``MetricsRegistry``: counters / gauges / fixed-bucket histograms.
  The engine/fleet/cache/chaos ``stats()`` dicts publish into it under
  namespaced keys ("engine.preemptions", "fleet.failovers", ...), so
  the registry is the unified cross-subsystem view and the per-call
  dicts are views over the same numbers (parity is pinned by
  tests/test_telemetry.py); span durations and ITL/TTFT/latency
  samples additionally feed fixed-bucket histograms live.
- ``Reservoir``: seeded Algorithm-R uniform sampling — the bound on
  the raw per-token ITL sample aggregation in ServingEngine.stats() /
  Router.stats() (exact below capacity, p50/p99-within-tolerance
  above it).

The program observatory (ISSUE 14) adds the PROGRAM-level half — the
requests were observable, the compiled programs the engine lives on
were not:

- ``CompileWatch``: the runtime twin of flightcheck's static FC2xx
  recompilation rules. Every serving program family registers its
  jitted callable; after each dispatch the engine asks the watch to
  compare the jit cache size against its ledger — growth IS a
  trace+lower+compile, recorded as an explicit ``compile`` span in the
  trace (family, operand-shape signature, wall; XLA
  ``cost_analysis()``/``memory_analysis()`` flops/bytes when
  ``analyze=True`` and the jax version exposes them) and counted in
  the registry. ``seal()`` declares the program set complete (after
  warmup): ANY later compile increments ``unexpected_recompiles`` and
  fires an ``unexpected_recompile`` event carrying the offending
  signature — a silent mid-serving XLA retrace stops being an
  unexplained ITL spike and becomes an assertable gate failure.
  Detection reads only the jit cache size (two host attribute reads
  per dispatch), so the steady state pays nothing.
- counter tracks: ``Tracer.counter(name, value, pid)`` records gauge
  samples that export as Perfetto ``ph: "C"`` counter events, so
  resource timelines (running slots, free/cached blocks, queue depth,
  in-flight chunks, acceptance EMA, per-replica load) render next to
  the request spans.
- ``SLOPolicy`` / ``SLOMonitor``: declared per-class latency targets
  (ttft/itl pXX) evaluated over multi-duration sliding windows with
  SRE-style burn rates (observed violation fraction over the allowed
  error budget); surfaced through ``stats()["slo"]`` and the Router's
  per-replica headroom rollup — the input SLO-aware routing needs.
- ``MetricsRegistry.to_openmetrics()`` / ``openmetrics_text()``: a
  jax-free OpenMetrics/Prometheus text exporter over the registry
  snapshot (``tools/metrics_export.py`` runs it standalone over an
  exported trace).

Overhead contract: ``tracer=None`` (the default everywhere) is a
BITWISE no-op — every hook is behind an ``if tracer is not None``
guard, no PRNG key is drawn, no device call is made, no schedule array
changes. Enabled, the hot path appends small dicts to a deque and
never touches a traced array or forces a host sync (the tracer reads
only host-side scheduler state — flightcheck's FC301 family stays at
zero findings over this module and its call sites); the serving bench
pins the enabled overhead < 5% tok/s on the ragged row
(bench.py serving_trace).

Export format: Chrome Trace Event JSON (the ``traceEvents`` array
form), loadable by Perfetto (ui.perfetto.dev) and chrome://tracing.
Request lifecycles are nestable async events (``ph: "b"/"e"``, matched
on ``cat + id`` across process tracks); per-phase slices are complete
events (``ph: "X"`` with ``ts``/``dur``); per-step events are instants
(``ph: "i"``). Engine events land on ``pid = replica_id``; fleet-level
records (routing, breaker, migration, the request async spans) land on
the dedicated ``FLEET_PID`` track.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from bisect import bisect_right
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Tracer", "MetricsRegistry", "Reservoir", "CompileWatch",
           "SLOPolicy", "SLOMonitor", "FLEET_PID",
           "DEFAULT_TIME_BUCKETS_S", "openmetrics_text"]

# the pid Chrome-trace track fleet-level records render on (routing,
# breaker transitions, migration, request async spans); engine records
# use pid = replica_id (0 for a single engine), so the two can never
# collide for any plausible fleet size
FLEET_PID = 1000

# fixed histogram buckets for second-valued observations (ITL, TTFT,
# latency, span durations): roughly log-spaced 0.5 ms .. 60 s
DEFAULT_TIME_BUCKETS_S = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
    1.0, 2.0, 5.0, 10.0, 30.0, 60.0)


class Reservoir:
    """Seeded Algorithm-R reservoir: a bounded uniform sample of an
    unbounded stream. Exact (every sample retained, in order) while the
    stream is <= k items; beyond that each seen item has equal
    probability k/n of being retained, so quantiles stay within
    sampling tolerance while memory is O(k). Deterministic: the same
    seed + the same stream reproduces the same sample (the RNG is
    private — engine PRNG streams are untouched)."""

    def __init__(self, k: int = 4096, seed: int = 0):
        self.k = int(k)
        self._rng = np.random.RandomState(seed)
        self.samples: List[float] = []
        self.n = 0                      # items seen (>= len(samples))

    def append(self, x: float):
        if self.n < self.k:
            self.samples.append(float(x))
        else:
            j = int(self._rng.randint(0, self.n + 1))
            if j < self.k:
                self.samples[j] = float(x)
        self.n += 1

    def extend(self, xs: Sequence[float]):
        for x in xs:
            self.append(x)

    def __len__(self) -> int:
        return len(self.samples)

    def __iter__(self):
        return iter(self.samples)

    @staticmethod
    def merge(parts, k: int = 4096, seed: int = 0) -> List[float]:
        """Combine several (samples, n_seen) parts — Reservoir objects
        or (list, n) tuples — into ONE bounded sample whose composition
        is proportional to each part's true stream size (concatenating
        raw reservoirs would over-weight small streams). Exact
        concatenation when everything fits in k."""
        norm = []
        for p in parts:
            if isinstance(p, Reservoir):
                norm.append((p.samples, p.n))
            else:
                s, n = p
                norm.append((list(s), int(n)))
        norm = [(s, n) for s, n in norm if s]
        total = sum(n for _, n in norm)
        if total <= k:
            return [x for s, _ in norm for x in s]
        rng = np.random.RandomState(seed)
        out: List[float] = []
        for s, n in norm:
            want = max(1, int(round(k * n / total)))
            if want >= len(s):
                out.extend(s)
            else:
                idx = rng.choice(len(s), size=want, replace=False)
                out.extend(s[i] for i in idx)
        return out


class _Histogram:
    """Fixed-bucket histogram: counts[i] = observations <= buckets[i]
    boundary (last slot is the overflow), plus n/sum for means."""

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(float(b) for b in buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.n = 0
        self.sum = 0.0

    def observe(self, v: float, n: int = 1):
        self.counts[bisect_right(self.buckets, float(v))] += int(n)
        self.n += int(n)
        self.sum += float(v) * int(n)

    def snapshot(self) -> dict:
        return {"buckets": list(self.buckets),
                "counts": list(self.counts),
                "n": self.n, "sum": self.sum,
                "mean": (self.sum / self.n) if self.n else None}


class MetricsRegistry:
    """Unified counters/gauges/histograms across engine, fleet, cache
    and chaos. Two feeding paths:

    - live: ``inc(name)`` from the tracer's event/span hooks (event
      counts, span-duration histograms) — cheap dict ops;
    - published: ``publish(prefix, stats_dict)`` mirrors a subsystem's
      ``stats()`` dict under namespaced keys (ints -> counters, floats
      -> gauges; None/bool/nested values skipped), making the stats
      dicts views over the registry — ``registry.value("engine.X") ==
      engine.stats()["X"]`` for every numeric key (tested).

    Thread-safety: all dict membership mutations and ``snapshot()``
    take one lock, so a watchdog-thread export can never hit a
    dictionary-changed-during-iteration crash while the engine thread
    records a first-seen event/histogram name. Individual histogram
    ``observe`` calls stay lockless (they mutate an existing object in
    place); a concurrent snapshot may read a histogram mid-update,
    which is tolerable for a post-mortem."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, _Histogram] = {}

    def inc(self, name: str, n: float = 1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, v: float):
        with self._lock:
            self.gauges[name] = float(v)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS_S
                  ) -> _Histogram:
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.get(name)
                if h is None:
                    h = self.histograms[name] = _Histogram(buckets)
        return h

    def publish(self, prefix: str, stats: dict):
        with self._lock:
            for key, v in stats.items():
                name = f"{prefix}.{key}"
                if v is None:
                    # a stat that went back to None (e.g. percentiles
                    # after clear_finished) must not leave its stale
                    # pre-reset value in the registry/export
                    self.counters.pop(name, None)
                    self.gauges.pop(name, None)
                    continue
                if isinstance(v, bool):
                    continue
                if isinstance(v, (int, np.integer)):
                    self.counters[name] = int(v)
                elif isinstance(v, (float, np.floating)):
                    self.gauges[name] = float(v)

    def value(self, name: str):
        with self._lock:
            if name in self.counters:
                return self.counters[name]
            return self.gauges.get(name)

    def snapshot(self) -> dict:
        with self._lock:
            return {"counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "histograms": {k: h.snapshot()
                                   for k, h in self.histograms.items()}}

    def to_openmetrics(self) -> str:
        """The registry as OpenMetrics/Prometheus text (counters with
        the ``_total`` suffix, gauges, cumulative-bucket histograms,
        terminated by ``# EOF``). Pure host formatting — scrapeable by
        any Prometheus-compatible collector; ``tools/metrics_export.py``
        runs the same formatter over an exported trace's snapshot."""
        return openmetrics_text(self.snapshot())


def _om_name(name: str) -> str:
    """Sanitize a dotted registry name into the OpenMetrics charset
    ([a-zA-Z0-9_:], non-digit first)."""
    s = "".join(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                else "_" for ch in str(name))
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def _om_num(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".10g")


def openmetrics_text(snapshot: dict) -> str:
    """Format a ``MetricsRegistry.snapshot()`` dict as OpenMetrics /
    Prometheus text exposition. jax-free on purpose: the exporter must
    run anywhere the snapshot JSON does (a metrics sidecar, a laptop
    reading a trace artifact — see tools/metrics_export.py)."""
    lines: List[str] = []
    for name, v in sorted((snapshot.get("counters") or {}).items()):
        n = _om_name(name)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n}_total {_om_num(v)}")
    for name, v in sorted((snapshot.get("gauges") or {}).items()):
        n = _om_name(name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_om_num(v)}")
    for name, h in sorted((snapshot.get("histograms") or {}).items()):
        n = _om_name(name)
        lines.append(f"# TYPE {n} histogram")
        cum = 0
        counts = list(h.get("counts", ()))
        buckets = list(h.get("buckets", ()))
        for b, c in zip(buckets, counts):
            cum += int(c)
            lines.append(f'{n}_bucket{{le="{_om_num(b)}"}} {cum}')
        if counts:
            cum += int(counts[-1])        # the overflow slot
        lines.append(f'{n}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{n}_sum {_om_num(h.get('sum', 0.0))}")
        lines.append(f"{n}_count {int(h.get('n', 0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class CompileWatch:
    """Per-program-family compile ledger + sealed-set retrace sentinel
    (ISSUE 14) — the runtime twin of flightcheck's static FC2xx rules.

    The engine registers every jitted serving program family
    (``register``), then calls ``observe(fn, t0, t1, args)`` after each
    dispatch. Detection is the jit cache size: growth since the last
    observation means that call TRACED+LOWERED+COMPILED (the call wall
    is the compile wall, execution being async), independent of any
    host-side model of what should retrace — a weak-type flip, a dtype
    drift or an unstable cache key is caught exactly like a new shape.
    The offending operand-shape signature is derived lazily (compiles
    only), so the steady state pays two host attribute reads.

    ``seal()`` declares the program set complete — warmup's contract.
    Any compile observed after sealing increments
    ``unexpected_recompiles`` and fires an ``unexpected_recompile``
    tracer event with the signature; chaos legs and the serving bench
    assert the counter stays zero.

    jax-free by duck typing: the jitted callable just needs
    ``_cache_size()`` (and ``lower()`` for the opt-in ``analyze``
    mode); a callable without it simply isn't watched."""

    MAX_RECORDS = 512

    def __init__(self, tracer: Optional["Tracer"] = None,
                 analyze: bool = False):
        self.tracer = tracer
        self.metrics = (tracer.metrics if tracer is not None
                        else MetricsRegistry())
        # analyze=True: on the FIRST observed compile of each family,
        # re-lower abstractly and pull XLA cost/memory analysis
        # (flops / bytes accessed / temp+output bytes) into the compile
        # record. Costs one extra trace+lower+compile per family —
        # off by default so traced production runs keep the <5%
        # overhead contract; tests and one-off investigations opt in.
        self.analyze = bool(analyze)
        self.pid = 0
        self.sealed = False
        self.compiles = 0
        self.unexpected_recompiles = 0
        self.records: List[dict] = []
        self._families: Dict[str, dict] = {}
        self._by_id: Dict[int, str] = {}

    def bind(self, tracer: Optional["Tracer"], pid: int = 0):
        """(Re)attach the tracer/registry sink and the replica pid —
        called by ServingEngine.set_telemetry."""
        self.tracer = tracer
        if tracer is not None:
            self.metrics = tracer.metrics
        self.pid = int(pid)

    @staticmethod
    def _size(jfn) -> int:
        try:
            return int(jfn._cache_size())
        except Exception:       # noqa: BLE001 — unwatchable callable
            return -1

    def register(self, family: str, jfn, **info):
        """Track one jitted program family. ``info`` (decoder build
        fingerprint, tp degree, ...) rides every compile record."""
        self._families[family] = {"fn": jfn, "size": self._size(jfn),
                                  "info": dict(info), "analyzed": False}
        self._by_id[id(jfn)] = family

    def family_of(self, fn) -> Optional[str]:
        return self._by_id.get(id(fn))

    @property
    def families(self) -> List[str]:
        return list(self._families)

    @staticmethod
    def signature_of(args, skip: int = 3, limit: int = 200) -> str:
        """Compact dtype[shape] signature of the VARYING operands —
        the first ``skip`` args (weights, k, v by the engine's calling
        convention) are engine-static and elided."""
        parts: List[str] = []

        def walk(x):
            if isinstance(x, (tuple, list)):
                for y in x:
                    walk(y)
            elif isinstance(x, dict):
                for k in sorted(x):
                    walk(x[k])
            elif hasattr(x, "shape") and hasattr(x, "dtype"):
                shape = "x".join(str(int(d)) for d in x.shape)
                dt = np.dtype(x.dtype).str.lstrip("<>|=")
                parts.append(f"{dt}[{shape}]")

        for a in list(args)[skip:]:
            walk(a)
        sig = ",".join(parts)
        return sig if len(sig) <= limit else sig[:limit] + "..."

    def _analyze(self, fn, args) -> dict:
        """Best-effort AOT lower/compile for XLA cost+memory analysis.
        Duck-typed and fully guarded: a jax version (or a sharded
        program) that refuses any step just yields fewer fields."""
        out: Dict[str, float] = {}
        try:
            t0 = time.perf_counter()
            lowered = fn.lower(*args)
            out["lower_s"] = time.perf_counter() - t0
        except Exception:       # noqa: BLE001 — best-effort contract
            return out
        try:
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                if "flops" in ca:
                    out["flops"] = float(ca["flops"])
                if "bytes accessed" in ca:
                    out["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:       # noqa: BLE001
            pass
        try:
            t0 = time.perf_counter()
            compiled = lowered.compile()
            out["compile_s"] = time.perf_counter() - t0
            ma = compiled.memory_analysis()
            out["temp_bytes"] = float(
                getattr(ma, "temp_size_in_bytes", 0))
            out["output_bytes"] = float(
                getattr(ma, "output_size_in_bytes", 0))
            out["argument_bytes"] = float(
                getattr(ma, "argument_size_in_bytes", 0))
        except Exception:       # noqa: BLE001
            pass
        return out

    def observe(self, fn, t0: float, t1: float, args=()
                ) -> "tuple[int, int]":
        """Post-dispatch check: did this call grow ``fn``'s jit cache?
        Returns (new_compiles, unexpected_compiles). A cache that
        SHRANK (jax.clear_caches between bench suites) just resyncs."""
        name = self._by_id.get(id(fn))
        if name is None:
            return 0, 0
        fam = self._families[name]
        if fam["size"] < 0:
            return 0, 0
        cur = self._size(fn)
        if cur < 0:
            fam["size"] = -1
            return 0, 0
        prev = fam["size"]
        fam["size"] = cur
        if cur <= prev:
            return 0, 0
        n = cur - prev
        wall = max(0.0, float(t1) - float(t0))
        rec = {"family": name, "signature": self.signature_of(args),
               "wall_s": wall, "sealed": self.sealed}
        rec.update(fam["info"])
        if self.analyze and not fam["analyzed"]:
            fam["analyzed"] = True
            rec.update(self._analyze(fn, args))
        self.compiles += n
        if len(self.records) < self.MAX_RECORDS:
            self.records.append(rec)
        m = self.metrics
        m.inc("compile.total", n)
        m.inc(f"compile.{name}")
        m.histogram("compile.wall_s").observe(wall)
        if "flops" in rec:
            m.set_gauge(f"compile.{name}.flops", rec["flops"])
        if "bytes_accessed" in rec:
            m.set_gauge(f"compile.{name}.bytes_accessed",
                        rec["bytes_accessed"])
        if self.tracer is not None:
            attrs = {k: v for k, v in rec.items() if k != "wall_s"}
            self.tracer.span("compile", None, t0, t1, pid=self.pid,
                             **attrs)
        unexpected = n if self.sealed else 0
        if unexpected:
            self.unexpected_recompiles += unexpected
            m.inc("compile.unexpected", unexpected)
            if self.tracer is not None:
                self.tracer.event("unexpected_recompile", pid=self.pid,
                                  family=name, signature=rec["signature"])
        return n, unexpected

    def seal(self):
        """Declare the program set complete: resync every family's
        cache size, then flag every later compile as unexpected (the
        runtime FC2xx — asserted zero by chaos legs and the bench)."""
        for fam in self._families.values():
            if fam["size"] >= 0:
                fam["size"] = self._size(fam["fn"])
        self.sealed = True
        self.metrics.set_gauge("compile.sealed", 1.0)
        if self.tracer is not None:
            self.tracer.event("programs_sealed", pid=self.pid,
                              families=len(self._families))


@dataclass
class SLOPolicy:
    """One declared latency objective over a traffic class: "p99 TTFT
    under ``ttft_p99_s`` and p99 ITL under ``itl_p99_s`` for requests
    matched by ``class_selector``" (None targets are unmonitored; a
    None selector matches all traffic). ``class_selector`` receives a
    small attrs dict ({"adapter_id": ..., "priority": ...}) so classes
    can be cut by tenant or priority without the monitor knowing the
    Request type."""
    name: str
    ttft_p99_s: Optional[float] = None
    itl_p99_s: Optional[float] = None
    class_selector: Optional[Callable[[dict], bool]] = None
    quantile: float = 0.99


class SLOMonitor:
    """Sliding-window SLO evaluation with multi-window burn rates.

    Samples arrive timestamped from the engine's collection paths
    (``observe``; ttft once per request, itl per delivered token with a
    count so a T-token chunk is one append). ``evaluate`` computes, per
    policy and metric, the observed quantile plus the BURN RATE of each
    window — (violating fraction) / (allowed fraction, 1 - quantile) —
    the SRE error-budget form: burn 1.0 spends the budget exactly,
    14.4x on a 1h window is the classic page threshold. A policy is
    ``violating`` when both the shortest and longest populated windows
    burn above 1.0 (the multi-window AND: a transient spike or a stale
    long tail alone doesn't page). ``headroom`` is (target - pXX) /
    target over the longest populated window, the per-replica scalar
    the fleet Router rolls up for SLO-aware routing (1.0 = idle/no
    data, negative = violating by that relative margin).

    Deterministic and jax-free: tests drive it with synthetic
    timestamps (``now=``); the engine feeds perf_counter."""

    DEFAULT_WINDOWS_S = (60.0, 300.0, 1800.0)
    METRICS = ("ttft", "itl")

    def __init__(self, policies, windows_s: Optional[Sequence[float]]
                 = None, max_samples: int = 4096):
        if isinstance(policies, SLOPolicy):
            policies = [policies]
        self.policies: List[SLOPolicy] = list(policies)
        names = [p.name for p in self.policies]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO policy names: {names}")
        self.windows = tuple(sorted(
            float(w) for w in (windows_s or self.DEFAULT_WINDOWS_S)))
        if not self.windows or any(w <= 0 for w in self.windows):
            raise ValueError(f"windows_s must be positive: "
                             f"{self.windows}")
        self.max_samples = int(max_samples)
        # (policy, metric) -> deque of (ts, value, count); bounded like
        # the PR-12 reservoirs so unbounded runs stay O(k)
        self._dq: Dict[tuple, deque] = {
            (p.name, m): deque(maxlen=self.max_samples)
            for p in self.policies for m in self.METRICS}

    @staticmethod
    def coerce_policies(slo) -> List[SLOPolicy]:
        """Normalize the ``slo=`` constructor surface (None / one
        policy / a monitor whose policies serve as the template / a
        sequence of policies) into a plain policy list — shared by
        ServingEngine and Router so the accepted forms can't drift."""
        if slo is None:
            return []
        if isinstance(slo, SLOMonitor):
            return list(slo.policies)
        if isinstance(slo, SLOPolicy):
            return [slo]
        return list(slo)

    @staticmethod
    def _target(p: SLOPolicy, metric: str) -> Optional[float]:
        return p.ttft_p99_s if metric == "ttft" else p.itl_p99_s

    def observe(self, metric: str, value: float, attrs: Optional[dict]
                = None, n: int = 1, now: Optional[float] = None):
        if metric not in self.METRICS:
            raise ValueError(f"metric must be one of {self.METRICS}, "
                             f"got {metric!r}")
        now = time.perf_counter() if now is None else float(now)
        for p in self.policies:
            if self._target(p, metric) is None:
                continue
            sel = p.class_selector
            if sel is not None and not sel(attrs or {}):
                continue
            self._dq[(p.name, metric)].append(
                (now, float(value), int(n)))

    def evaluate(self, now: Optional[float] = None) -> dict:
        now = time.perf_counter() if now is None else float(now)
        policies: Dict[str, dict] = {}
        any_viol = False
        heads: List[float] = []
        for p in self.policies:
            metrics: Dict[str, dict] = {}
            p_viol = False
            p_heads: List[float] = []
            for metric in self.METRICS:
                target = self._target(p, metric)
                if target is None:
                    continue
                samples = list(self._dq[(p.name, metric)])
                allowed = max(1e-9, 1.0 - p.quantile)
                wins: Dict[str, dict] = {}
                burns: List[float] = []
                for w in self.windows:
                    vals = [(v, k) for ts, v, k in samples
                            if now - ts <= w]
                    nn = sum(k for _, k in vals)
                    bad = sum(k for v, k in vals if v > target)
                    burn = ((bad / nn) / allowed) if nn else None
                    wins[f"{int(w)}s"] = {
                        "n": nn, "violations": bad,
                        "burn_rate": (round(burn, 4)
                                      if burn is not None else None)}
                    if nn:
                        burns.append(burn)
                pxx = None
                longest = [(v, k) for ts, v, k in samples
                           if now - ts <= self.windows[-1]]
                if longest:
                    arr = np.repeat([v for v, _ in longest],
                                    [k for _, k in longest])
                    pxx = float(np.quantile(arr, p.quantile))
                viol = (len(burns) > 0 and burns[0] > 1.0
                        and burns[-1] > 1.0)
                head = (None if pxx is None
                        else (target - pxx) / target)
                metrics[metric] = {
                    "target_s": target,
                    "p_s": (round(pxx, 6) if pxx is not None else None),
                    "windows": wins, "violating": viol,
                    "headroom": (round(head, 4)
                                 if head is not None else None)}
                p_viol = p_viol or viol
                if head is not None:
                    p_heads.append(head)
            head = min(p_heads) if p_heads else 1.0
            policies[p.name] = {"metrics": metrics,
                                "violating": p_viol,
                                "headroom": round(head, 4)}
            any_viol = any_viol or p_viol
            heads.append(head)
        return {"policies": policies, "violating": any_viol,
                "min_headroom": (round(min(heads), 4)
                                 if heads else 1.0)}

    def reset(self):
        """Drop every window (the clear_finished contract: post-warmup
        stats reflect only real traffic)."""
        for dq in self._dq.values():
            dq.clear()


class Tracer:
    """Flight recorder + span tracer. See the module docstring for the
    taxonomy; the record stream is a bounded deque of small dicts:

    - ``{"kind": "begin"/"end", "name": "request", "trace": id, ...}``
      — request lifecycle (async span endpoints);
    - ``{"kind": "span", "name": phase, "trace": id, "ts": t0,
      "dur": seconds, ...}`` — one completed per-life phase;
    - ``{"kind": "event", "name": ..., ...}`` — per-step instants.

    Timestamps are ``time.perf_counter()`` values (the engine's own
    clock); export rebases them to microseconds from the tracer's
    construction. Thread-safe (the watchdog thread reads ``summary()``
    while the engine appends)."""

    DEFAULT_CAPACITY = 1 << 16

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 metrics: Optional[MetricsRegistry] = None,
                 id_base: int = 1):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.appended = 0
        self.metrics = metrics or MetricsRegistry()
        # id_base (ISSUE 19): a worker-process Tracer starts its trace
        # ids at a per-(replica, generation) disjoint base, so records
        # forwarded over the transport and ingested into the parent
        # ring can never collide with the parent's own ids (default 1:
        # single-process behavior unchanged)
        self._ids = itertools.count(int(id_base))
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------------
    def _record(self, rec: dict):
        with self._lock:
            self._ring.append(rec)
            self.appended += 1

    @property
    def dropped(self) -> int:
        """Records that fell off the ring (flight-recorder semantics:
        the newest ``capacity`` records always survive)."""
        return self.appended - len(self._ring)

    def begin_request(self, req_id: int, tenant=None, replica: int = 0,
                      **attrs) -> int:
        """Open one request-lifetime async span; returns its trace id
        (propagate it through adopt_request so a migrated request stays
        ONE span)."""
        tid = next(self._ids)
        args = {"req_id": int(req_id), "replica": int(replica)}
        if tenant is not None:
            args["tenant"] = str(tenant)
        args.update(attrs)
        self._record({"kind": "begin", "name": "request", "trace": tid,
                      "pid": FLEET_PID, "ts": time.perf_counter(),
                      "args": args})
        self.metrics.inc("trace.requests")
        return tid

    def end_request(self, trace_id: Optional[int], state: str,
                    replica: int = 0, **attrs):
        if trace_id is None:
            return
        args = {"state": state, "replica": int(replica)}
        args.update(attrs)
        self._record({"kind": "end", "name": "request",
                      "trace": int(trace_id), "pid": FLEET_PID,
                      "ts": time.perf_counter(), "args": args})
        self.metrics.inc(f"trace.requests_{state}")

    def reopen_request(self, trace_id: Optional[int]) -> bool:
        """Rescind the most recent end record of ``trace_id`` — the
        fleet Router calls this when it migrates a request whose
        fault-burst FAILURE already closed the span (the engine failed
        it before the breaker tripped): the migration supersedes the
        terminal state, so the span must stay open until the adopted
        continuation ends it (one continuous span across replicas).
        Returns False when no end record is in the ring (it either
        never existed or already fell off)."""
        if trace_id is None:
            return False
        with self._lock:
            for r in reversed(self._ring):
                if r["kind"] == "end" and r["trace"] == trace_id:
                    self._ring.remove(r)
                    self.appended -= 1
                    state = r["args"].get("state")
                    if state:
                        self.metrics.inc(f"trace.requests_{state}", -1)
                    return True
        return False

    def span(self, name: str, trace_id: Optional[int], t0: float,
             t1: float, pid: int = 0, **attrs):
        """One completed per-life phase slice [t0, t1] (perf_counter
        seconds) on the replica track ``pid``."""
        self._record({"kind": "span", "name": name,
                      "trace": (int(trace_id) if trace_id is not None
                                else None),
                      "pid": int(pid), "ts": float(t0),
                      "dur": max(0.0, float(t1) - float(t0)),
                      "args": attrs})
        self.metrics.inc(f"spans.{name}")
        self.metrics.histogram(f"span.{name}_s").observe(
            max(0.0, float(t1) - float(t0)))

    def event(self, name: str, trace: Optional[int] = None,
              pid: int = 0, **attrs):
        """One per-step instant (dispatch, retry, injected fault,
        breaker strike, kv alloc/evict/splice/rollback, ...)."""
        self._record({"kind": "event", "name": name,
                      "trace": (int(trace) if trace is not None
                                else None),
                      "pid": int(pid), "ts": time.perf_counter(),
                      "args": attrs})
        self.metrics.inc(f"events.{name}")

    def counter(self, name: str, value, pid: int = 0):
        """One counter-track sample (ISSUE 14): exports as a Perfetto
        ``ph: "C"`` event so the value renders as a resource TIMELINE
        next to the request spans (running slots, free blocks, queue
        depth, ...). The latest value also lands in the registry as a
        ``track.*`` gauge (per-replica suffix off the pid), so the
        OpenMetrics export carries the instantaneous view."""
        v = float(value)
        self._record({"kind": "counter", "name": name, "trace": None,
                      "pid": int(pid), "ts": time.perf_counter(),
                      "args": {"value": v}})
        suffix = ("" if pid == 0
                  else ".fleet" if pid == FLEET_PID
                  else f".r{int(pid)}")
        self.metrics.set_gauge(f"track.{name}{suffix}", v)

    # -- cross-process forwarding (ISSUE 19) ---------------------------------
    def drain_since(self, mark: int) -> tuple:
        """``(records appended since `mark`, new mark)`` — the worker
        side of transport telemetry forwarding: each step/stats reply
        piggybacks only the NEW records (reconstructed from the ring
        tail via the ``appended`` counter; records that already fell
        off the ring are lost exactly like flight-recorder semantics
        lose them locally)."""
        with self._lock:
            new = self.appended - int(mark)
            if new <= 0:
                return [], self.appended
            recs = list(self._ring)
            return (recs[-new:] if new < len(recs) else recs,
                    self.appended)

    def ingest(self, records: List[dict], ts_offset: float = 0.0):
        """Append records forwarded from ANOTHER process's Tracer into
        this ring, mirroring each kind's registry side-effects (the
        merged registry / validate_trace / trace_report views must
        agree with a single-process run). ``ts_offset`` shifts worker
        timestamps onto the parent clock — 0.0 on Linux, where
        perf_counter is CLOCK_MONOTONIC and shared across processes."""
        for r in records:
            rec = dict(r)
            if ts_offset:
                rec["ts"] = float(rec["ts"]) + ts_offset
            self._record(rec)
            kind, name = rec.get("kind"), rec.get("name")
            if kind == "begin":
                self.metrics.inc("trace.requests")
            elif kind == "end":
                state = rec.get("args", {}).get("state")
                if state:
                    self.metrics.inc(f"trace.requests_{state}")
            elif kind == "span":
                self.metrics.inc(f"spans.{name}")
                self.metrics.histogram(f"span.{name}_s").observe(
                    max(0.0, float(rec.get("dur", 0.0))))
            elif kind == "event":
                self.metrics.inc(f"events.{name}")
            elif kind == "counter":
                pid = int(rec.get("pid", 0))
                suffix = ("" if pid == 0
                          else ".fleet" if pid == FLEET_PID
                          else f".r{pid}")
                self.metrics.set_gauge(
                    f"track.{name}{suffix}",
                    float(rec["args"]["value"]))

    # -- reading -------------------------------------------------------------
    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def summary(self, last: int = 25) -> str:
        """Human-readable tail of the flight recorder (the watchdog
        appends this to its hang report)."""
        recs = self.records()
        lines = [f"flight recorder: {self.appended} records "
                 f"({self.dropped} dropped, capacity {self.capacity}); "
                 f"last {min(last, len(recs))}:"]
        for r in recs[-last:]:
            t = r["ts"] - self._t0
            extra = f" dur={r['dur'] * 1e3:.2f}ms" if "dur" in r else ""
            tidp = f" trace={r['trace']}" if r.get("trace") else ""
            lines.append(f"  +{t:9.3f}s [{r['kind']}] {r['name']}"
                         f"{tidp} pid={r['pid']}{extra} {r['args']}")
        return "\n".join(lines) + "\n"

    # -- export --------------------------------------------------------------
    def _us(self, t: float) -> float:
        return max(0.0, (t - self._t0) * 1e6)

    def export(self, path: str) -> str:
        """Write the flight recorder as Chrome-trace / Perfetto JSON
        (plus the metrics-registry snapshot under ``"metrics"``).
        Returns ``path``."""
        recs = self.records()
        evts: List[dict] = []
        pids = sorted({r["pid"] for r in recs})
        for pid in pids:
            name = ("fleet" if pid == FLEET_PID
                    else f"replica{pid}")
            evts.append({"ph": "M", "name": "process_name", "pid": pid,
                         "tid": 0, "ts": 0,
                         "args": {"name": name}})
        for r in recs:
            tid = r["trace"] if r.get("trace") is not None else 0
            if r["kind"] == "begin":
                evts.append({"ph": "b", "cat": "request",
                             "id": str(r["trace"]),
                             "name": f"req{r['args'].get('req_id', '')}",
                             "pid": r["pid"], "tid": tid,
                             "ts": self._us(r["ts"]),
                             "args": r["args"]})
            elif r["kind"] == "end":
                evts.append({"ph": "e", "cat": "request",
                             "id": str(r["trace"]), "name": "request",
                             "pid": r["pid"], "tid": tid,
                             "ts": self._us(r["ts"]),
                             "args": r["args"]})
            elif r["kind"] == "span":
                evts.append({"ph": "X", "cat": "phase",
                             "name": r["name"], "pid": r["pid"],
                             "tid": tid, "ts": self._us(r["ts"]),
                             "dur": r["dur"] * 1e6,
                             "args": r["args"]})
            elif r["kind"] == "counter":
                evts.append({"ph": "C", "cat": "track",
                             "name": r["name"], "pid": r["pid"],
                             "tid": 0, "ts": self._us(r["ts"]),
                             "args": r["args"]})
            else:
                evts.append({"ph": "i", "cat": "step",
                             "name": r["name"], "pid": r["pid"],
                             "tid": tid, "ts": self._us(r["ts"]),
                             "s": "t", "args": r["args"]})
        doc = {"traceEvents": evts, "displayTimeUnit": "ms",
               "otherData": {"dropped_records": self.dropped,
                             "appended_records": self.appended},
               "metrics": self.metrics.snapshot()}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
