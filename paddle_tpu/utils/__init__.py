"""paddle_tpu.utils — flags registry, misc helpers."""
from .flags import get_flags, set_flags, define_flag  # noqa: F401
