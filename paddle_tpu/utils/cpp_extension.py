"""Custom C++ operator extension — XLA FFI custom calls.

Reference: paddle.utils.cpp_extension + the C++ custom-op registry
(/root/reference/paddle/fluid/framework/custom_operator.cc,
paddle/phi/capi/ — PD_BUILD_OP macros compiled out-of-tree and loaded at
runtime). TPU-native split (SURVEY.md §2.5 item 22):

- **Host/C++ ops**: compiled against XLA's FFI headers
  (jax.ffi.include_dir()) into a shared library; handlers register as
  XLA custom-call targets on the host platform. This is the analog of
  the reference's custom CPU kernels.
- **Device (TPU) ops**: written as Pallas kernels in Python (see
  paddle_tpu/ops/pallas) — the TPU has no user C++ path in any
  framework; the reference's CUDA custom ops map to Pallas here.
- **Pure-Python ops with custom gradients**: ``register_custom_op``
  wraps forward/backward into a jax.custom_vjp dispatched through the
  framework tape (the PD_BUILD_OP + grad-op analog without C++).

Typical C++ handler (compiled by ``load``):

    #include "xla/ffi/api/ffi.h"
    namespace ffi = xla::ffi;
    static ffi::Error AxpyImpl(float a, ffi::Buffer<ffi::F32> x,
                               ffi::Buffer<ffi::F32> y,
                               ffi::ResultBuffer<ffi::F32> out) { ... }
    XLA_FFI_DEFINE_HANDLER_SYMBOL(Axpy, AxpyImpl,
        ffi::Ffi::Bind().Attr<float>("a").Arg<ffi::Buffer<ffi::F32>>()
            .Arg<ffi::Buffer<ffi::F32>>().Ret<ffi::Buffer<ffi::F32>>());
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["load", "CustomOpModule", "register_custom_op", "get_build_dir"]

_BUILD_DIR = os.environ.get(
    "PADDLE_TPU_EXTENSION_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu_extensions"))
_lock = threading.Lock()


def get_build_dir() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    return _BUILD_DIR


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Sequence[str] = (),
             extra_include_paths: Sequence[str] = (),
             verbose: bool = False) -> str:
    import hashlib
    # cache key covers sources AND flags: changed -D flags must rebuild,
    # and two extensions sharing a name must not collide
    sig = hashlib.sha1("\0".join(
        [*sorted(sources), *extra_cxx_flags,
         *extra_include_paths]).encode()).hexdigest()[:12]
    out = os.path.join(get_build_dir(), f"{name}_{sig}.so")
    if os.path.exists(out) and all(
            os.path.getmtime(s) <= os.path.getmtime(out) for s in sources):
        return out
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared",
           f"-I{jax.ffi.include_dir()}",
           *[f"-I{p}" for p in extra_include_paths],
           *extra_cxx_flags, *sources, "-o", out]
    if verbose:
        print("[cpp_extension]", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"custom op build failed:\n{proc.stderr[-3000:]}")
    return out


class CustomOpModule:
    """Loaded extension: each registered handler becomes a callable that
    issues the XLA custom call (host platform)."""

    def __init__(self, name: str, so_path: str):
        self.name = name
        self.so_path = so_path
        self._lib = ctypes.CDLL(so_path)
        self._registered: Dict[str, str] = {}

    def register(self, target_name: str, symbol: Optional[str] = None,
                 platform: str = "cpu") -> "CustomOpModule":
        """Register the exported handler `symbol` (default: target_name)
        as custom-call target `target_name`."""
        sym = symbol or target_name
        fn = getattr(self._lib, sym)
        capsule = jax.ffi.pycapsule(fn)
        jax.ffi.register_ffi_target(target_name, capsule,
                                    platform=platform)
        self._registered[target_name] = platform
        return self

    def call(self, target_name: str, out_shape, out_dtype, *args,
             **attrs):
        """Invoke the custom call. args: Tensors/arrays; attrs become FFI
        attributes. Works under jit (it's a real XLA custom call)."""
        from ..framework.core import Tensor, apply
        out_type = jax.ShapeDtypeStruct(tuple(out_shape), out_dtype)

        def f(*arrays):
            call = jax.ffi.ffi_call(target_name, out_type)
            return call(*arrays, **attrs)

        return apply(f"custom_call:{target_name}", f, *args)

    def make_op(self, target_name: str, out_shape_fn: Callable,
                out_dtype_fn: Optional[Callable] = None, **fixed_attrs):
        """Bind a python-callable op: shapes inferred per-call via
        out_shape_fn(*input_shapes) (the InferMeta analog for custom
        ops)."""
        def op(*args, **attrs):
            shapes = [tuple(a.shape) for a in args]
            out_shape = out_shape_fn(*shapes)
            dt = out_dtype_fn(*args) if out_dtype_fn else args[0].dtype
            merged = dict(fixed_attrs)
            merged.update(attrs)
            return self.call(target_name, out_shape, dt, *args, **merged)
        op.__name__ = target_name
        return op


def load(name: str, sources: Sequence[str],
         extra_cxx_flags: Sequence[str] = (),
         extra_include_paths: Sequence[str] = (),
         verbose: bool = False) -> CustomOpModule:
    """Compile + load a custom-op extension (reference
    cpp_extension.load parity). Returns a CustomOpModule; call
    .register(target) for each exported handler."""
    with _lock:
        so = _compile(name, list(sources), extra_cxx_flags,
                      extra_include_paths, verbose)
    return CustomOpModule(name, so)


# ---------------------------------------------------------------------------
# Pure-Python custom op with custom gradient (PD_BUILD_OP analog)
# ---------------------------------------------------------------------------

_custom_ops: Dict[str, Callable] = {}


def register_custom_op(name: str, forward: Callable,
                       backward: Optional[Callable] = None) -> Callable:
    """Register op `name` with array-level forward(*arrays) and optional
    backward(residuals, *cotangents) -> input cotangents. The returned
    callable dispatches through the autograd tape; under jit it traces
    like any framework op.

    When a backward is given, forward MUST return (primal, residuals) —
    the PD_BUILD_OP forward/grad contract.
    """
    from ..framework.core import apply

    if backward is None:
        fn = forward
    else:
        @jax.custom_vjp
        def fn(*arrays):
            return forward(*arrays)[0]

        def fwd(*arrays):
            return forward(*arrays)  # (primal, residuals)

        def bwd(res, ct):
            grads = backward(res, ct)
            return grads if isinstance(grads, tuple) else (grads,)

        fn.defvjp(fwd, bwd)

    has_backward = backward is not None

    def op(*args, **kwargs):
        if kwargs:
            if has_backward:
                raise ValueError(
                    f"custom op {name!r} with a custom backward cannot "
                    f"take keyword args (jax.custom_vjp limitation); "
                    f"close over them in forward/backward instead")
            return apply(name, lambda *a: fn(*a, **kwargs), *args)
        return apply(name, lambda *a: fn(*a), *args)

    op.__name__ = name
    _custom_ops[name] = op
    return op
