"""Version shims so one source tree spans the jax releases we run on.

The code targets the modern surface (``jax.shard_map`` & friends); older
installs (0.4.x) spell the same objects under ``jax.experimental``. The
shims alias the new names onto the ``jax`` module BEFORE any paddle_tpu
module imports them — `from jax import shard_map` is an attribute lookup
at import time, so patching here is enough. No behavior changes: every
alias points at the identical implementation object.
"""
from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
        import inspect as _inspect

        if "check_vma" in _inspect.signature(_shard_map).parameters:
            jax.shard_map = _shard_map
        else:
            # pre-rename shard_map: check_vma was check_rep, and
            # "manual over a subset" was spelled auto=<complement set>
            # instead of axis_names=<manual set>
            import functools as _functools

            @_functools.wraps(_shard_map)
            def _shard_map_compat(f, *args, **kwargs):
                if "check_vma" in kwargs:
                    kwargs["check_rep"] = kwargs.pop("check_vma")
                names = kwargs.pop("axis_names", None)
                if names is not None:
                    mesh = kwargs.get("mesh", args[0] if args else None)
                    kwargs["auto"] = (frozenset(mesh.axis_names)
                                      - frozenset(names))
                return _shard_map(f, *args, **kwargs)

            jax.shard_map = _shard_map_compat
    except ImportError:  # pragma: no cover — very old jax; leave as-is
        pass

# jax.lax.pvary (newer VMA tagging) is value-identity; the old rep
# system either skips the check (check_rep=False) or infers reps itself
if not hasattr(jax.lax, "pvary"):
    jax.lax.pvary = lambda x, axis_name=None: x

# 0.4.x ships jax.export as a submodule but does not import it into the
# jax namespace by default (attribute access lands in the deprecation
# __getattr__ and raises); importing it here registers the attribute
try:
    import jax.export  # noqa: F401
except ImportError:  # pragma: no cover
    pass

# jax.P (PartitionSpec alias) appeared alongside jax.shard_map
if not hasattr(jax, "P"):
    try:
        from jax.sharding import PartitionSpec as _P
        jax.P = _P
    except ImportError:  # pragma: no cover
        pass

# jax.ffi graduated from jax.extend.ffi; alias the old module forward
if not hasattr(jax, "ffi"):
    try:
        import jax.extend.ffi as _ffi
        jax.ffi = _ffi
    except ImportError:  # pragma: no cover
        pass

# pallas-TPU renamed TPUCompilerParams -> CompilerParams; alias forward
try:
    import jax.experimental.pallas.tpu as _pltpu
    if not hasattr(_pltpu, "CompilerParams") and \
            hasattr(_pltpu, "TPUCompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:  # pragma: no cover
    pass
