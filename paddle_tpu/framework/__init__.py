from . import dtype
from .core import (
    Tensor, Parameter, apply, apply_nodiff, no_grad, enable_grad,
    is_grad_enabled, to_tensor, set_device, get_device, seed,
    get_rng_state, set_rng_state, default_generator, Generator, with_rng_key,
)
from .dtype import (
    convert_dtype, get_default_dtype, set_default_dtype,
)

__all__ = [
    "Tensor", "Parameter", "apply", "apply_nodiff", "no_grad", "enable_grad",
    "is_grad_enabled", "to_tensor", "set_device", "get_device", "seed",
    "get_rng_state", "set_rng_state", "default_generator", "Generator",
    "with_rng_key", "convert_dtype", "get_default_dtype", "set_default_dtype",
    "dtype",
]
