"""Dtype registry and promotion helpers.

TPU-native analog of the reference's dtype plumbing
(/root/reference/paddle/phi/common/data_type.h): instead of a C++ enum +
promotion tables, we alias JAX/NumPy dtypes under Paddle-style names and lean
on jnp's promotion (which matches XLA semantics). bfloat16 is first-class —
it is the TPU MXU's native matmul dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (numpy dtype instances; jnp.bfloat16 is ml_dtypes).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # Paddle-style aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}


def convert_dtype(dtype):
    """Normalize a dtype-like (str, np.dtype, jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return np.dtype(_STR2DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    d = np.dtype(dtype)
    return any(d == np.dtype(f) for f in _FLOATING)


def is_integer(dtype) -> bool:
    return np.dtype(dtype).kind in ("i", "u")


def is_complex(dtype) -> bool:
    return np.dtype(dtype).kind == "c"


# Default dtype management (paddle.get_default_dtype / set_default_dtype).
_default_dtype = np.dtype(np.float32)


def set_default_dtype(dtype):
    global _default_dtype
    d = convert_dtype(dtype)
    if not is_floating_point(d):
        raise TypeError(f"Default dtype must be floating point, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
