"""Core Tensor type and eager autograd engine.

TPU-native re-imagination of the reference's eager stack:

- ``Tensor`` is a thin facade over ``jax.Array`` (the reference's
  ``paddle::Tensor``, /root/reference/paddle/phi/api/include/tensor.h:82).
- The eager autograd engine replaces the codegen'd C++ grad nodes
  (/root/reference/paddle/fluid/eager/grad_node_info.h:197 and
  backward.cc:105) with a tape of ``jax.vjp`` closures: every differentiable
  op call records one ``TapeNode``; ``Tensor.backward()`` runs a reverse
  topological sweep, exactly like Paddle's ``RunBackward`` in-degree queue,
  but each node's backward is a JAX VJP (so XLA compiles/fuses the math).
- There is no kernel registry/dispatcher: XLA *is* the kernel library. The
  ``apply`` dispatcher below only does tape recording + AMP autocast, the
  analog of the generated ``xxx_ad_func`` wrappers
  (/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py).

Under a JAX trace (the jit/to_static path), the same op implementations run
on tracers; the functional train-step path bypasses the tape entirely and
uses ``jax.grad`` — see paddle_tpu/jit.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

__all__ = [
    "Tensor",
    "Parameter",
    "apply",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "to_tensor",
    "set_device",
    "get_device",
    "seed",
    "get_rng_state",
    "set_rng_state",
    "default_generator",
    "Generator",
    "with_rng_key",
]


# --------------------------------------------------------------------------
# Grad mode
# --------------------------------------------------------------------------

class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


@contextlib.contextmanager
def no_grad():
    prev = _grad_state.enabled
    _grad_state.enabled = False
    try:
        yield
    finally:
        _grad_state.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _grad_state.enabled
    _grad_state.enabled = True
    try:
        yield
    finally:
        _grad_state.enabled = prev


# --------------------------------------------------------------------------
# Device management
# --------------------------------------------------------------------------

_current_device: Optional[jax.Device] = None


def _resolve_device(spec: str) -> jax.Device:
    spec = spec.lower()
    if ":" in spec:
        kind, idx = spec.split(":")
        idx = int(idx)
    else:
        kind, idx = spec, 0
    # Accept paddle-style names; 'gpu' maps to whatever accelerator is local.
    if kind in ("tpu", "gpu", "xpu", "accelerator", "axon"):
        devs = [d for d in jax.devices() if d.platform != "cpu"]
        if not devs:
            devs = jax.devices()
    elif kind == "cpu":
        devs = jax.devices("cpu")
    else:
        devs = jax.devices()
    return devs[idx % len(devs)]


def set_device(device: str):
    """paddle.set_device analog. Returns the selected jax.Device."""
    global _current_device
    _current_device = _resolve_device(device)
    return _current_device


def get_device() -> str:
    if _current_device is None:
        d = jax.devices()[0]
    else:
        d = _current_device
    name = "cpu" if d.platform == "cpu" else "tpu"
    return f"{name}:{d.id}"


def current_jax_device() -> Optional[jax.Device]:
    return _current_device


# --------------------------------------------------------------------------
# RNG: Paddle-style global seed over JAX threaded PRNG keys.
# Reference: phi::Generator (/root/reference/paddle/phi/core/generator.h) —
# here a splittable key stream; under jit a traced base key can be pushed so
# random ops inside compiled train steps stay functional.
# --------------------------------------------------------------------------

class Generator:
    def __init__(self, seed_: int = 0):
        self._seed = int(seed_)
        self._key_ = None  # lazy: importing the framework must not
        self._traced_key = None  # initialize a JAX backend (launcher CLI,
        self._traced_counter = 0  # fork-based dataloader workers)

    @property
    def _key(self):
        if self._key_ is None:
            self._key_ = jax.random.PRNGKey(self._seed)
        return self._key_

    @_key.setter
    def _key(self, v):
        self._key_ = v

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._key_ = jax.random.PRNGKey(self._seed)
        return self

    @property
    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Return a fresh PRNG key. Inside a with_rng_key() scope the keys
        derive from the traced base key (safe under jax.jit); otherwise the
        concrete global key is split."""
        if self._traced_key is not None:
            self._traced_counter += 1
            return jax.random.fold_in(self._traced_key, self._traced_counter)
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return np.asarray(self._key)

    def set_state(self, state):
        self._key = jnp.asarray(state, dtype=jnp.uint32)
        return self


default_generator = Generator(0)


def seed(value: int):
    """paddle.seed analog."""
    default_generator.manual_seed(value)
    return default_generator


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


@contextlib.contextmanager
def with_rng_key(key):
    """Thread a (possibly traced) base key through eager-style random ops so
    they remain pure under jax.jit. Used by jit.TrainStep and dropout."""
    prev = (default_generator._traced_key, default_generator._traced_counter)
    default_generator._traced_key = key
    default_generator._traced_counter = 0
    try:
        yield
    finally:
        default_generator._traced_key, default_generator._traced_counter = prev


# --------------------------------------------------------------------------
# Autograd tape
# --------------------------------------------------------------------------

class TapeNode:
    """One recorded differentiable op (analog of a codegen'd GradNode,
    /root/reference/paddle/fluid/eager/grad_node_info.h:197). Holds the
    jax.vjp closure (which owns the saved residuals — the analog of
    TensorWrapper saved tensors) and edges to input tensors."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "op_name", "id", "multi")

    _counter = 0

    def __init__(self, vjp_fn, inputs, out_avals, op_name, multi=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs            # List[Tensor] at recorded positions
        self.out_avals = out_avals      # List[jax.ShapeDtypeStruct]
        self.op_name = op_name
        # whether the recorded fn returned a tuple (vjp cotangent structure)
        self.multi = len(out_avals) > 1 if multi is None else multi
        TapeNode._counter += 1
        self.id = TapeNode._counter


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _run_backward(root: "Tensor", grad_arr, retain_graph: bool,
                  accum_fn=None):
    """Reverse topological sweep — analog of egr::RunBackward
    (/root/reference/paddle/fluid/eager/backward.cc:105).

    accum_fn(tensor, grad_array): leaf-gradient sink; defaults to
    Tensor._accum_grad (i.e. populate .grad). paddle.grad() passes a
    collector so it never touches .grad of uninvolved leaves."""
    if accum_fn is None:
        accum_fn = Tensor._accum_grad
    root_node = root._node
    if root_node is None:
        if not root.stop_gradient:
            accum_fn(root, grad_arr)
        return

    # DFS topo order over the node DAG.
    order: List[TapeNode] = []
    visited = set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if node.id in visited:
            continue
        visited.add(node.id)
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and t._node.id not in visited:
                stack.append((t._node, False))

    # Seed cotangent.
    node_grads = {root_node.id: [None] * len(root_node.out_avals)}
    node_grads[root_node.id][root._out_idx] = grad_arr

    for node in reversed(order):
        grads = node_grads.pop(node.id, None)
        if grads is None:
            continue
        cotangents = []
        for g, aval in zip(grads, node.out_avals):
            if g is None:
                if np.issubdtype(aval.dtype, np.integer) or \
                        aval.dtype == np.bool_:
                    # non-differentiable output: vjp expects float0
                    cotangents.append(
                        np.zeros(aval.shape, jax.dtypes.float0))
                else:
                    cotangents.append(jnp.zeros(aval.shape, aval.dtype))
            else:
                cotangents.append(g)
        ct = tuple(cotangents) if node.multi else cotangents[0]
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through a graph that has already been "
                "freed; call backward(retain_graph=True) to backward twice")
        in_grads = node.vjp_fn(ct)
        for t, g in zip(node.inputs, in_grads):
            if g is None or _is_float0(g):
                continue
            if t._node is not None:
                slot = node_grads.setdefault(t._node.id, [None] * len(t._node.out_avals))
                prev = slot[t._out_idx]
                slot[t._out_idx] = g if prev is None else prev + g
            elif not t.stop_gradient:
                accum_fn(t, g)
        if not retain_graph:
            node.vjp_fn = None

    if not retain_graph:
        for node in order:
            node.inputs = ()


# --------------------------------------------------------------------------
# Tensor
# --------------------------------------------------------------------------

_tensor_method_registry = {}

# When set, Tensor._replace records every mutated Tensor and
# Tensor.__init__ every created one — to_static's plain-function path
# uses this to detect writes to PRE-EXISTING state (buffers/globals)
# that tracing would silently drop (jit/__init__.py).
_mutation_watch = None


class _watch_mutations:
    """Yields (mutated_ids -> Tensor, created_ids) for the with-block."""

    def __enter__(self):
        global _mutation_watch
        self._prev = _mutation_watch
        _mutation_watch = ({}, set())
        return _mutation_watch

    def __exit__(self, *exc):
        global _mutation_watch
        _mutation_watch = self._prev
        return False


class Tensor:
    """Eager tensor: a jax.Array plus autograd metadata.

    ``stop_gradient`` follows Paddle semantics (True by default; Parameters
    default to False). Most methods are monkey-patched from paddle_tpu.tensor
    at import time — mirroring Paddle's math_op_patch
    (/root/reference/python/paddle/base/dygraph/math_op_patch.py:60)."""

    __slots__ = ("_value", "stop_gradient", "grad", "_node", "_out_idx",
                 "name", "persistable", "trainable", "is_leaf_",
                 "process_mesh", "placements", "_opt_state_placements",
                 "__weakref__")

    def __init__(self, value, stop_gradient: bool = True, name: str = ""):
        if _mutation_watch is not None:
            _mutation_watch[1].add(id(self))
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self.is_leaf_ = True
        self.process_mesh = None
        self.placements = None
        # ZeRO-1/2: optimizer-state placements may differ from the
        # param's own (states sharded while params stay replicated)
        self._opt_state_placements = None

    # -- basic properties ---------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def place(self):
        try:
            dev = next(iter(self._value.devices()))
            return f"{dev.platform}:{dev.id}"
        except Exception:
            return "traced"

    def numpy(self):
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_s = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}"
                f"{grad_s},\n       {np.asarray(jax.device_get(self._value)) if not self._is_traced() else self._value})")

    def _is_traced(self) -> bool:
        return isinstance(self._value, jax.core.Tracer)

    def __bool__(self):
        return bool(self._value)

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False):
        """Analog of Tensor.backward →
        /root/reference/paddle/fluid/eager/backward.cc:428 (egr::Backward)."""
        if self.stop_gradient and self._node is None:
            raise RuntimeError("backward() on a tensor with no grad graph")
        if grad_tensor is None:
            if self.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward()")
            g = jnp.ones(self._value.shape, self._value.dtype)
        else:
            g = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
        _run_backward(self, g, retain_graph)

    def _accum_grad(self, g):
        if g.dtype != self._value.dtype:
            g = g.astype(self._value.dtype)
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True, name=self.name + "@GRAD")
        else:
            self.grad = Tensor(self.grad._value + g, stop_gradient=True,
                               name=self.name + "@GRAD")

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value), True)
        else:
            self.grad = None

    def clear_grad(self):
        self.clear_gradient()

    def detach(self) -> "Tensor":
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def clone(self) -> "Tensor":
        return apply("clone", lambda x: x + 0, self)

    # -- mutation (in-place value replacement) ------------------------------
    def _replace(self, new_value):
        """Replace the underlying array (optimizer updates, buffer updates).
        Breaks no autograd invariants because leaves have no recorded node."""
        if _mutation_watch is not None:
            _mutation_watch[0][id(self)] = self
        # partial-capture placeholders unwrap to their concrete array
        # once materialized (jit/partial_capture._SymValue)
        unwrap = getattr(new_value, "_pt_unwrap", None)
        if unwrap is not None:
            new_value = unwrap()
        self._value = new_value

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        arr = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(arr.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._value.shape}")
        # keep the destination's sharding (checkpoint load into DistTensor)
        old_sharding = getattr(self._value, "sharding", None)
        if old_sharding is not None and not self._is_traced() and \
                not isinstance(arr, jax.core.Tracer):
            try:
                arr = jax.device_put(arr, old_sharding)
            except Exception:
                pass
        self._replace(arr)

    def copy_(self, other):
        self.set_value(other)
        return self

    # -- conversion ---------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        d = dtypes.convert_dtype(dtype)
        return apply("cast", lambda x: x.astype(d), self)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or ":" in str(a):
                dev = _resolve_device(str(a))
                t = Tensor(jax.device_put(t._value, dev), t.stop_gradient, t.name)
            else:
                t = t.astype(a)
        return t

    def cpu(self):
        return Tensor(jax.device_get(self._value), self.stop_gradient, self.name)

    def pin_memory(self):
        return self

    def cuda(self):  # paddle API compat; routes to the accelerator
        return self.to("tpu")

    # -- registration hook for monkey patching ------------------------------
    @classmethod
    def _register_method(cls, name: str, fn: Callable):
        _tensor_method_registry[name] = fn
        setattr(cls, name, fn)


class Parameter(Tensor):
    """Trainable leaf tensor (analog of paddle's ParamBase /
    EagerParamBase). stop_gradient defaults to False."""

    def __init__(self, value, trainable: bool = True, name: str = ""):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# --------------------------------------------------------------------------
# Op dispatch: record-on-tape wrapper.
# --------------------------------------------------------------------------

def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def as_jnp(x):
    """Coerce Tensor / ndarray / python scalar to a jnp array."""
    return jnp.asarray(_unwrap(x))


_amp_hook: Optional[Callable] = None  # installed by paddle_tpu.amp


def _set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


# Static-graph recorder hook (installed by paddle_tpu.static): when static
# mode is on and any arg is symbolic, ops append graph nodes instead of
# executing — the analog of OpDesc appending to the default main Program
# (/root/reference/python/paddle/base/framework.py), except the "IR" is a
# DAG of pure jax thunks and shape inference is jax.eval_shape.
_static_handler: Optional[Callable] = None


def _set_static_handler(fn):
    global _static_handler
    _static_handler = fn


# Partial-graph capture handler (jit/partial_capture.py — the SOT analog:
# /root/reference/python/paddle/jit/sot/opcode_translator/executor/
# opcode_executor.py). Receives (op_name, fn, args, kwargs, diff);
# NotImplemented defers to the normal eager path.
_capture_handler: Optional[Callable] = None


def _set_capture_handler(fn):
    global _capture_handler
    _capture_handler = fn


# Numerics-checker + op-stats hooks (installed by paddle_tpu.amp.debugging
# — the FLAGS_check_nan_inf / op-stats analog of the reference's
# paddle/fluid/eager/nan_inf_utils.h). Both receive (op_name, out_arrays).
_check_hook: Optional[Callable] = None
_stats_hook: Optional[Callable] = None


def _set_check_hook(fn):
    global _check_hook
    _check_hook = fn


def _set_stats_hook(fn):
    global _stats_hook
    _stats_hook = fn


def apply(op_name: str, fn: Callable, *args: Any, **kwargs: Any):
    """Run ``fn`` over the unwrapped jax arrays of ``args``, recording a
    TapeNode when gradients are required. ``fn`` must be pure; non-Tensor
    args pass through as captured constants.

    This is the analog of one generated ``xxx_ad_func``
    (/root/reference/paddle/fluid/eager/auto_code_generator/generator/eager_gen.py):
    AMP autocast → (optional) grad-node creation → kernel invocation, except
    the 'kernel' is a jnp/lax composition compiled by XLA.
    """
    if _static_handler is not None:
        out = _static_handler(op_name, fn, args, kwargs)
        if out is not NotImplemented:
            return out
    if _capture_handler is not None:
        out = _capture_handler(op_name, fn, args, kwargs, True)
        if out is not NotImplemented:
            return out
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensors = [args[i] for i in tensor_pos]

    if _amp_hook is not None:
        tensors = _amp_hook(op_name, tensors)

    arrs = tuple(t._value for t in tensors)

    def pure(*xs):
        full = list(args)
        for i, x in zip(tensor_pos, xs):
            full[i] = x
        return fn(*full, **kwargs)

    need_grad = (_grad_state.enabled
                 and any(not t.stop_gradient for t in tensors))

    if need_grad:
        outs, vjp_fn = jax.vjp(pure, *arrs)
    else:
        outs = pure(*arrs)

    multi = isinstance(outs, (tuple, list))
    outs_list = list(outs) if multi else [outs]

    if _check_hook is not None:
        _check_hook(op_name, outs_list)
    if _stats_hook is not None:
        _stats_hook(op_name, outs_list)

    result = [Tensor(o, stop_gradient=not need_grad) for o in outs_list]

    if need_grad:
        node = TapeNode(
            vjp_fn,
            tensors,
            [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs_list],
            op_name,
            multi=multi,
        )
        for k, t in enumerate(result):
            t._node = node
            t._out_idx = k
            t.is_leaf_ = False

    if multi:
        return tuple(result)
    return result[0]


def apply_nodiff(op_name: str, fn: Callable, *args, **kwargs):
    """Dispatch for non-differentiable ops (argmax, comparisons, ...)."""
    if _static_handler is not None:
        out = _static_handler(op_name, fn, args, kwargs)
        if out is not NotImplemented:
            return out
    if _capture_handler is not None:
        out = _capture_handler(op_name, fn, args, kwargs, False)
        if out is not NotImplemented:
            return out
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    full = list(args)
    for i in tensor_pos:
        full[i] = args[i]._value
    outs = fn(*full, **kwargs)
    multi = isinstance(outs, (tuple, list))
    outs_list = list(outs) if multi else [outs]
    if _check_hook is not None:
        _check_hook(op_name, outs_list)
    if _stats_hook is not None:
        _stats_hook(op_name, outs_list)
    result = [Tensor(o, stop_gradient=True) for o in outs_list]
    return tuple(result) if multi else result[0]


# --------------------------------------------------------------------------
# Creation
# --------------------------------------------------------------------------

def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor analog."""
    if isinstance(data, Tensor):
        arr = data._value
        if dtype is not None:
            arr = arr.astype(dtypes.convert_dtype(dtype))
        return Tensor(arr, stop_gradient=stop_gradient)
    d = dtypes.convert_dtype(dtype) if dtype is not None else None
    if d is None and isinstance(data, (float,)):
        d = dtypes.get_default_dtype()
    if d is None and isinstance(data, (list, tuple)) and _contains_float(data):
        d = dtypes.get_default_dtype()
    if d is None and isinstance(data, np.ndarray) and data.dtype == np.float64:
        d = dtypes.get_default_dtype()
    arr = jnp.asarray(data, dtype=d)
    dev = _resolve_device(place) if isinstance(place, str) else _current_device
    if dev is not None and not isinstance(arr, jax.core.Tracer):
        arr = jax.device_put(arr, dev)
    return Tensor(arr, stop_gradient=stop_gradient)


def _contains_float(x) -> bool:
    if isinstance(x, float):
        return True
    if isinstance(x, (list, tuple)):
        return any(_contains_float(e) for e in x)
    return False
