"""paddle.save / paddle.load parity
(/root/reference/python/paddle/framework/io.py:721,960): pickle-based
state_dict persistence. Tensors serialize as numpy arrays."""
from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .core import Tensor, Parameter

__all__ = ["save", "load"]

_PROTOCOL = 4


class _TensorPickle:
    def __init__(self, array: np.ndarray, is_param: bool, name: str,
                 stop_gradient: bool, dtype_name: str):
        self.array = array
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient
        self.dtype_name = dtype_name


def _pack(obj: Any) -> Any:
    if isinstance(obj, Tensor):
        arr = np.asarray(jax.device_get(obj._value))
        return _TensorPickle(arr, isinstance(obj, Parameter), obj.name,
                             obj.stop_gradient, str(obj._value.dtype))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any, return_numpy: bool = False) -> Any:
    if isinstance(obj, _TensorPickle):
        if return_numpy:
            return obj.array
        arr = jnp.asarray(obj.array)
        if obj.dtype_name == "bfloat16":
            arr = arr.astype(jnp.bfloat16)
        if obj.is_param:
            p = Parameter(arr, trainable=not obj.stop_gradient, name=obj.name)
            return p
        t = Tensor(arr, stop_gradient=obj.stop_gradient, name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = _PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
