"""paddle.signal parity (reference:
/root/reference/python/paddle/signal.py — frame, overlap_add, stft,
istft). Framing is a gather/reshape — static shapes, XLA-fusable.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor, as_jnp as _v

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along ``axis``.

    Output places the frame dim: axis=-1 → (..., frame_length, n_frames);
    axis=0 → (n_frames, frame_length, ...), matching the reference.
    """
    v = _v(x)
    fl, hop = int(frame_length), int(hop_length)
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    n = v.shape[-1] if axis == -1 else v.shape[0]
    if n < fl:
        raise ValueError(
            f"signal length {n} is shorter than frame_length {fl}")
    n_frames = 1 + (n - fl) // hop
    idx = (jnp.arange(fl)[:, None] + hop * jnp.arange(n_frames)[None, :])
    if axis == -1:
        out = jnp.take(v, idx, axis=-1)          # (..., fl, n_frames)
    else:
        out = jnp.take(v, idx.T, axis=0)          # (n_frames, fl, ...)
    return Tensor(out)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: sum overlapping frames back into a signal."""
    v = _v(x)
    hop = int(hop_length)
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1")
    if axis == 0:
        # (n_frames, frame_length, ...) → canonical (..., fl, n_frames)
        v = jnp.moveaxis(jnp.moveaxis(v, 0, -1), 0, -2)
    fl, n_frames = v.shape[-2], v.shape[-1]
    out_len = fl + hop * (n_frames - 1)
    idx = (jnp.arange(fl)[:, None] + hop * jnp.arange(n_frames)[None, :])
    flat = v.reshape(v.shape[:-2] + (-1,))
    out = jnp.zeros(v.shape[:-2] + (out_len,), v.dtype)
    out = out.at[..., idx.reshape(-1)].add(flat)
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return Tensor(out)


def _get_window(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    w = _v(window)
    return w.astype(dtype)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode='reflect', normalized=False, onesided=True,
         name=None):
    v = _v(x)
    squeeze = False
    if v.ndim == 1:
        v, squeeze = v[None], True
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _get_window(window, win_length, v.dtype)
    if win_length < n_fft:   # center-pad window to n_fft
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))
    if center:
        v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)],
                    mode=pad_mode)
    frames = _v(frame(Tensor(v), n_fft, hop_length, axis=-1))
    frames = frames * w[:, None]
    frames = jnp.moveaxis(frames, -1, -2)        # (..., n_frames, n_fft)
    if onesided:
        spec = jnp.fft.rfft(frames, axis=-1)
    else:
        spec = jnp.fft.fft(frames, axis=-1)
    spec = jnp.moveaxis(spec, -1, -2)            # (..., n_bins, n_frames)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    if squeeze:
        spec = spec[0]
    return Tensor(spec)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    v = _v(x)
    squeeze = False
    if v.ndim == 2:
        v, squeeze = v[None], True
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    rdt = jnp.finfo(jnp.result_type(v.real)).dtype
    w = _get_window(window, win_length, rdt)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))
    spec = jnp.moveaxis(v, -1, -2)               # (..., n_frames, n_bins)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, rdt))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, n=n_fft, axis=-1)
        if not return_complex:
            frames = frames.real
    frames = frames * w                           # windowed synthesis
    frames_t = jnp.moveaxis(frames, -1, -2)       # (..., n_fft, n_frames)
    sig = _v(overlap_add(Tensor(frames_t), hop_length, axis=-1))
    wsq = jnp.broadcast_to((w * w)[:, None], frames_t.shape[-2:])
    norm = _v(overlap_add(Tensor(wsq), hop_length, axis=-1))
    sig = sig / jnp.where(norm > 1e-11, norm, 1.0)
    if center:
        sig = sig[..., n_fft // 2: sig.shape[-1] - n_fft // 2]
    if length is not None:
        sig = sig[..., :length]
    if squeeze:
        sig = sig[0]
    return Tensor(sig)
