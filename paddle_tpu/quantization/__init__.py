"""paddle_tpu.quantization — QAT/PTQ framework.

Reference: /root/reference/python/paddle/quantization/ (QuantConfig in
config.py, QAT in qat.py, PTQ in ptq.py, observers in observer.py,
fake quanters in quanters/). TPU-native: fake-quant is a
straight-through-estimator jnp composition (XLA fuses it into the
surrounding matmul); int8 execution maps to XLA int8 dot when converted.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver", "BaseQuanter",
           "MovingAverageAbsmaxObserver", "PerChannelAbsmaxObserver",
           "HistogramObserver", "KLObserver",
           "FakeQuanterWithAbsMaxObserver", "quanter", "QuantedLinear",
           "QuantedConv2D", "Int8Linear", "convert_to_int8"]


def _fake_quant(x, scale, bit_length=8):
    """Symmetric fake-quant with straight-through gradient."""
    bnd = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * bnd), -bnd, bnd) * s / bnd
    # STE: forward q, backward identity
    return x + jax.lax.stop_gradient(q - x)


class BaseObserver:
    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._scale = None

    def scale(self):
        return self._scale

    def observe(self, x_arr):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Per-tensor absmax (reference observer.py AbsmaxObserver)."""

    def observe(self, x_arr):
        m = jnp.max(jnp.abs(x_arr))
        self._scale = m if self._scale is None else jnp.maximum(
            self._scale, m)
        return self._scale


class MovingAverageAbsmaxObserver(BaseObserver):
    """EMA absmax (reference quanters/FakeQuanterWithAbsMaxObserver
    moving-average state)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def observe(self, x_arr):
        m = jnp.max(jnp.abs(x_arr))
        self._scale = m if self._scale is None else (
            self.moving_rate * self._scale + (1 - self.moving_rate) * m)
        return self._scale


class BaseQuanter(Layer):
    """Abstract quanter base (reference
    paddle.quantization.BaseQuanter): subclasses implement forward =
    fake-quantized pass plus scales()/zero_points()."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Activation/weight fake-quant layer used inside QAT-converted
    models."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype: str = "float32", name=None):
        super().__init__()
        self.bit_length = bit_length
        self.observer = MovingAverageAbsmaxObserver(bit_length, moving_rate)

    def forward(self, x):
        if self.training:
            self.observer.observe(jax.lax.stop_gradient(x._value))
        scale = self.observer.scale()
        if scale is None:
            return x
        return apply("fake_quant",
                     lambda a: _fake_quant(a, scale, self.bit_length), x)


def quanter(name: str):
    """Decorator registering custom quanter classes (reference
    factory.py quanter)."""
    def deco(cls):
        _QUANTERS[name] = cls
        return cls
    return deco


_QUANTERS: Dict[str, type] = {
    "FakeQuanterWithAbsMaxObserver": FakeQuanterWithAbsMaxObserver,
}


class QuantConfig:
    """Maps layers → quanter settings (reference config.py QuantConfig)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs: List[tuple] = []
        self._type_configs: Dict[type, tuple] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs.append((l, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)

    def _config_for(self, layer):
        for l, a, w in self._layer_configs:
            if l is layer:
                return a, w
        for t, (a, w) in self._type_configs.items():
            if isinstance(layer, t):
                return a, w
        return self.activation, self.weight


_DEFAULT = object()  # distinguishes "use default quanter" from
# "None = leave this tensor unquantized" (QuantConfig semantics)


class QuantedLinear(Layer):
    """Linear with weight/activation fake-quant (QAT form of nn.Linear;
    reference nn/quant/qat/linear.py). Pass None for either quanter to
    leave that tensor unquantized."""

    def __init__(self, linear, act_quanter=_DEFAULT,
                 weight_quanter=_DEFAULT):
        super().__init__()
        self.linear = linear
        self.act_quanter = FakeQuanterWithAbsMaxObserver() \
            if act_quanter is _DEFAULT else act_quanter
        self.weight_quanter = FakeQuanterWithAbsMaxObserver() \
            if weight_quanter is _DEFAULT else weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        xq = self.act_quanter(x) if self.act_quanter is not None else x
        w = self.linear.weight
        wq = self.weight_quanter(w) if self.weight_quanter is not None \
            else w
        return F.linear(xq, wq, self.linear.bias)


class QAT:
    """Quantization-aware training driver (reference qat.py QAT):
    quantize() swaps supported layers for quantized variants in-place on
    a model copy."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        from ..nn import Linear
        target = model if inplace else copy.deepcopy(model)
        self._convert(target)
        return target

    @staticmethod
    def _make(cfg):
        """Materialize a quanter from a config entry: registry name,
        quanter class, instance, or None (= do not quantize)."""
        if cfg is None:
            return None
        if isinstance(cfg, str):
            return _QUANTERS.get(cfg)()
        return cfg() if isinstance(cfg, type) else cfg

    def _convert(self, layer: Layer):
        from ..nn import Linear, Conv2D
        from ..nn.quant.stub import QuanterStub, Stub
        for name, sub in list(layer.named_children()):
            if isinstance(sub, Conv2D):
                a, w_cfg = self.config._config_for(sub)
                setattr(layer, name, QuantedConv2D(
                    sub, self._make(a), self._make(w_cfg)))
            elif isinstance(sub, Linear):
                a, w = self.config._config_for(sub)
                setattr(layer, name, QuantedLinear(
                    sub, self._make(a), self._make(w)))
            elif isinstance(sub, Stub):
                a, _w = self.config._config_for(sub)
                obs = sub._observer if sub._observer is not None else a
                setattr(layer, name, QuanterStub(self._make(obs)))
            else:
                self._convert(sub)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Post-training: freeze observers (eval mode model is already
        emitting fake-quant with learned scales)."""
        target = model if inplace else copy.deepcopy(model)
        target.eval()
        return target


class PTQ:
    """Post-training quantization driver (reference ptq.py PTQ):
    quantize() inserts observers; calibrate by running representative
    batches; convert() freezes."""

    def __init__(self, config: QuantConfig):
        self._qat = QAT(config)

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        q = self._qat.quantize(model, inplace)
        q.train()  # observers update during calibration passes
        return q

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        return self._qat.convert(model, inplace)


class PerChannelAbsmaxObserver(BaseObserver):
    """Per-output-channel absmax for weights (reference
    quanters/FakeQuanterChannelWiseAbsMaxObserver). `channel_axis` is the
    output-feature dim — 1 for paddle Linear's [in, out] layout, 0 for
    Conv's [out, in, kh, kw]."""

    def __init__(self, quant_bits: int = 8, channel_axis: int = 1):
        super().__init__(quant_bits)
        self.channel_axis = channel_axis

    def observe(self, x_arr):
        axes = tuple(i for i in range(x_arr.ndim)
                     if i != self.channel_axis)
        m = jnp.max(jnp.abs(x_arr), axis=axes)
        self._scale = m if self._scale is None else jnp.maximum(
            self._scale, m)
        return self._scale

    def broadcast_scale(self, ndim):
        shape = [1] * ndim
        shape[self.channel_axis] = -1
        return self._scale.reshape(shape)


class HistogramObserver(BaseObserver):
    """Percentile calibration over an accumulated |x| histogram
    (reference observers + slim HistQuanter): the scale is the
    `percent`-quantile of the observed magnitude distribution — robust to
    activation outliers that wreck plain absmax."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percent = percent
        self._hist = None
        self._upper = None

    def observe(self, x_arr):
        ax = jnp.abs(x_arr.astype(jnp.float32)).reshape(-1)
        m = float(jnp.max(ax))
        if self._hist is None:
            self._upper = max(m, 1e-9)
            self._hist = np.zeros(self.bins, np.float64)
        if m > self._upper:  # stretch: rebin old mass into the new range
            ratio = self._upper / m
            old = self._hist
            idx = (np.arange(self.bins) * ratio).astype(np.int64)
            stretched = np.zeros_like(old)
            np.add.at(stretched, idx, old)
            self._hist = stretched
            self._upper = m
        h, _ = np.histogram(np.asarray(ax), bins=self.bins,
                            range=(0.0, self._upper))
        self._hist += h
        cdf = np.cumsum(self._hist)
        cut = np.searchsorted(cdf, cdf[-1] * self.percent)
        self._scale = jnp.asarray(
            (cut + 1) / self.bins * self._upper, jnp.float32)
        return self._scale


class KLObserver(HistogramObserver):
    """KL-divergence calibration (TensorRT-style, the reference slim KL
    quanter): choose the clip threshold whose quantized distribution has
    minimal KL divergence from the observed one."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048):
        super().__init__(quant_bits, bins)

    def _kl(self, p, q):
        p = p / max(p.sum(), 1e-12)
        q = q / max(q.sum(), 1e-12)
        mask = p > 0
        qm = np.where(q > 0, q, 1e-12)
        return float(np.sum(p[mask] * np.log(p[mask] / qm[mask])))

    def observe(self, x_arr):
        super().observe(x_arr)   # maintain the histogram
        levels = 2 ** (self.quant_bits - 1)  # 128 for int8
        hist = self._hist
        best, best_div = self.bins, np.inf
        # candidate thresholds: from 2*levels bins up to the full range
        for cut in range(levels * 2, self.bins + 1, max(self.bins // 64, 1)):
            ref = hist[:cut].copy()
            ref[cut - 1] += hist[cut:].sum()   # clip mass into last bin
            # quantize: collapse cut bins into `levels` buckets and expand
            chunks = np.array_split(ref, levels)
            q = np.concatenate([
                np.full(len(c), c.sum() / max((c > 0).sum(), 1))
                * (c > 0) for c in chunks])
            div = self._kl(ref, q)
            if div < best_div:
                best_div, best = div, cut
        self._scale = jnp.asarray(best / self.bins * self._upper,
                                  jnp.float32)
        return self._scale


class QuantedConv2D(Layer):
    """Conv2D with weight/activation fake-quant (reference
    nn/quant/qat/conv.py). Weight scales are per-output-channel."""

    def __init__(self, conv, act_quanter=_DEFAULT, weight_quanter=_DEFAULT):
        super().__init__()
        self.conv = conv
        self.act_quanter = FakeQuanterWithAbsMaxObserver() \
            if act_quanter is _DEFAULT else act_quanter
        self.weight_observer = PerChannelAbsmaxObserver(channel_axis=0) \
            if weight_quanter is _DEFAULT else weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        xq = self.act_quanter(x) if self.act_quanter is not None else x
        w = self.conv.weight
        wq = self.weight_observer
        if isinstance(wq, Layer):        # quanter layer (per-tensor STE)
            w = wq(w)
        elif wq is not None:             # per-channel observer
            wq.observe(jax.lax.stop_gradient(w._value))
            scale = wq.broadcast_scale(w._value.ndim)
            w = apply("fake_quant_w", lambda a: _fake_quant(a, scale), w)
        return F.conv2d(xq, w, self.conv.bias, stride=self.conv.stride,
                        padding=self.conv.padding,
                        dilation=self.conv.dilation,
                        groups=self.conv.groups,
                        data_format=getattr(self.conv, "data_format",
                                            "NCHW"))


class Int8Linear(Layer):
    """CONVERTED linear: weights stored int8 (per-channel scales),
    activations quantized dynamically at the recorded calibration scale,
    matmul runs in int8 with int32 accumulation — XLA lowers this to the
    native int8 MXU path on TPU. Reference analog: the int8 kernels
    behind paddle slim's converted inference graphs."""

    def __init__(self, linear, act_scale, w_observer=None):
        super().__init__()
        w = linear.weight._value
        if w_observer is None:
            w_observer = PerChannelAbsmaxObserver(channel_axis=1)
            w_observer.observe(w)
        w_scale = w_observer.scale().astype(jnp.float32)   # [out]
        bnd = 127.0
        q = jnp.clip(jnp.round(w.astype(jnp.float32)
                               / jnp.maximum(w_scale, 1e-9) * bnd),
                     -bnd, bnd).astype(jnp.int8)
        self.register_buffer("qweight", Tensor(q))
        self.register_buffer("w_scale", Tensor(w_scale))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        self.bias = linear.bias

    def forward(self, x):
        def f(a, qw, ws, as_, *b):
            bnd = 127.0
            sa = jnp.maximum(as_, 1e-9)
            aq = jnp.clip(jnp.round(a.astype(jnp.float32) / sa * bnd),
                          -bnd, bnd).astype(jnp.int8)
            acc = jax.lax.dot_general(
                aq, qw, (((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (sa / bnd) * (ws / bnd)
            out = out.astype(a.dtype)
            if b:
                out = out + b[0]
            return out

        args = (x, self.qweight, self.w_scale, self.act_scale)
        if self.bias is not None:
            return apply("int8_linear", f, *args, self.bias)
        return apply("int8_linear", f, *args)


def convert_to_int8(model: Layer, inplace: bool = False) -> Layer:
    """Convert a calibrated QAT/PTQ model: every QuantedLinear whose
    observers hold scales becomes an Int8Linear executing the int8
    dot path."""
    target = model if inplace else copy.deepcopy(model)

    def _walk(layer):
        for name, sub in list(layer.named_children()):
            if isinstance(sub, QuantedLinear):
                aq = sub.act_quanter
                act_scale = None
                if aq is not None:
                    # standard quanters expose .observer.scale(); custom
                    # quanters may expose .scale() directly
                    ob = getattr(aq, "observer", aq)
                    scale_fn = getattr(ob, "scale", None)
                    if scale_fn is None:
                        raise RuntimeError(
                            f"convert_to_int8: activation quanter "
                            f"{type(aq).__name__} exposes no scale() — "
                            f"int8 conversion needs a calibrated scale "
                            f"(provide .observer.scale() or .scale())")
                    act_scale = scale_fn()
                if act_scale is None:
                    raise RuntimeError(
                        "convert_to_int8: activation scale missing — run "
                        "calibration batches through the quantized model "
                        "first (PTQ.quantize -> forward passes -> "
                        "convert)")
                setattr(layer, name, Int8Linear(sub.linear, act_scale))
            else:
                _walk(sub)

    _walk(target)
    target.eval()
    return target
