"""paddle_tpu.quantization — QAT/PTQ framework.

Reference: /root/reference/python/paddle/quantization/ (QuantConfig in
config.py, QAT in qat.py, PTQ in ptq.py, observers in observer.py,
fake quanters in quanters/). TPU-native: fake-quant is a
straight-through-estimator jnp composition (XLA fuses it into the
surrounding matmul); int8 execution maps to XLA int8 dot when converted.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "MovingAverageAbsmaxObserver", "FakeQuanterWithAbsMaxObserver",
           "quanter", "QuantedLinear"]


def _fake_quant(x, scale, bit_length=8):
    """Symmetric fake-quant with straight-through gradient."""
    bnd = float(2 ** (bit_length - 1) - 1)
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * bnd), -bnd, bnd) * s / bnd
    # STE: forward q, backward identity
    return x + jax.lax.stop_gradient(q - x)


class BaseObserver:
    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._scale = None

    def scale(self):
        return self._scale

    def observe(self, x_arr):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Per-tensor absmax (reference observer.py AbsmaxObserver)."""

    def observe(self, x_arr):
        m = jnp.max(jnp.abs(x_arr))
        self._scale = m if self._scale is None else jnp.maximum(
            self._scale, m)
        return self._scale


class MovingAverageAbsmaxObserver(BaseObserver):
    """EMA absmax (reference quanters/FakeQuanterWithAbsMaxObserver
    moving-average state)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def observe(self, x_arr):
        m = jnp.max(jnp.abs(x_arr))
        self._scale = m if self._scale is None else (
            self.moving_rate * self._scale + (1 - self.moving_rate) * m)
        return self._scale


class FakeQuanterWithAbsMaxObserver(Layer):
    """Activation/weight fake-quant layer used inside QAT-converted
    models."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype: str = "float32", name=None):
        super().__init__()
        self.bit_length = bit_length
        self.observer = MovingAverageAbsmaxObserver(bit_length, moving_rate)

    def forward(self, x):
        if self.training:
            self.observer.observe(jax.lax.stop_gradient(x._value))
        scale = self.observer.scale()
        if scale is None:
            return x
        return apply("fake_quant",
                     lambda a: _fake_quant(a, scale, self.bit_length), x)


def quanter(name: str):
    """Decorator registering custom quanter classes (reference
    factory.py quanter)."""
    def deco(cls):
        _QUANTERS[name] = cls
        return cls
    return deco


_QUANTERS: Dict[str, type] = {
    "FakeQuanterWithAbsMaxObserver": FakeQuanterWithAbsMaxObserver,
}


class QuantConfig:
    """Maps layers → quanter settings (reference config.py QuantConfig)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs: List[tuple] = []
        self._type_configs: Dict[type, tuple] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs.append((l, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)

    def _config_for(self, layer):
        for l, a, w in self._layer_configs:
            if l is layer:
                return a, w
        for t, (a, w) in self._type_configs.items():
            if isinstance(layer, t):
                return a, w
        return self.activation, self.weight


_DEFAULT = object()  # distinguishes "use default quanter" from
# "None = leave this tensor unquantized" (QuantConfig semantics)


class QuantedLinear(Layer):
    """Linear with weight/activation fake-quant (QAT form of nn.Linear;
    reference nn/quant/qat/linear.py). Pass None for either quanter to
    leave that tensor unquantized."""

    def __init__(self, linear, act_quanter=_DEFAULT,
                 weight_quanter=_DEFAULT):
        super().__init__()
        self.linear = linear
        self.act_quanter = FakeQuanterWithAbsMaxObserver() \
            if act_quanter is _DEFAULT else act_quanter
        self.weight_quanter = FakeQuanterWithAbsMaxObserver() \
            if weight_quanter is _DEFAULT else weight_quanter

    def forward(self, x):
        from ..nn import functional as F
        xq = self.act_quanter(x) if self.act_quanter is not None else x
        w = self.linear.weight
        wq = self.weight_quanter(w) if self.weight_quanter is not None \
            else w
        return F.linear(xq, wq, self.linear.bias)


class QAT:
    """Quantization-aware training driver (reference qat.py QAT):
    quantize() swaps supported layers for quantized variants in-place on
    a model copy."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        from ..nn import Linear
        target = model if inplace else copy.deepcopy(model)
        self._convert(target)
        return target

    def _convert(self, layer: Layer):
        from ..nn import Linear
        for name, sub in list(layer.named_children()):
            if isinstance(sub, Linear):
                a, w = self.config._config_for(sub)
                make = lambda cfg: (_QUANTERS.get(cfg)() if isinstance(
                    cfg, str) else (cfg() if isinstance(cfg, type)
                                    else cfg))
                # None in the config means: do not quantize that tensor
                setattr(layer, name, QuantedLinear(
                    sub, make(a) if a is not None else None,
                    make(w) if w is not None else None))
            else:
                self._convert(sub)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Post-training: freeze observers (eval mode model is already
        emitting fake-quant with learned scales)."""
        target = model if inplace else copy.deepcopy(model)
        target.eval()
        return target


class PTQ:
    """Post-training quantization driver (reference ptq.py PTQ):
    quantize() inserts observers; calibrate by running representative
    batches; convert() freezes."""

    def __init__(self, config: QuantConfig):
        self._qat = QAT(config)

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        q = self._qat.quantize(model, inplace)
        q.train()  # observers update during calibration passes
        return q

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        return self._qat.convert(model, inplace)
