"""paddle.regularizer parity
(/root/reference/python/paddle/regularizer.py): L1/L2 weight decay
objects accepted by optimizers' weight_decay argument. On TPU both fold
into the compiled update step (L2 is the optimizer's decoupled/coupled
decay; L1 adds a sign term)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(param)."""

    def apply_to_grad(self, param_arr, grad_arr):
        import jax.numpy as jnp
        return grad_arr + self.coeff * jnp.sign(param_arr)


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (coupled form; AdamW-style optimizers apply
    it decoupled instead)."""

    def apply_to_grad(self, param_arr, grad_arr):
        return grad_arr + self.coeff * param_arr
