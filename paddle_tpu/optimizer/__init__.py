"""paddle_tpu.optimizer — parity with paddle.optimizer."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta,
    RMSProp, Lamb,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
from .lbfgs import LBFGS, Rprop  # noqa: F401
