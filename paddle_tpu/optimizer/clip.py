"""Gradient clipping (parity: /root/reference/python/paddle/nn/clip.py).
Operates on raw grad arrays so the same code runs in eager step() and in
the jitted train step."""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def apply(self, grads):
        """grads: list of raw arrays (None allowed) → clipped list."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        out = []
        for g in grads:
            if g is None:
                out.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip. Under GSPMD the norm reduction is automatically a
    cross-replica psum when grads are sharded — the distributed-aware
    behavior of the reference's HybridParallelOptimizer
    (/root/reference/python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:254)
    falls out for free."""

    def __init__(self, clip_norm=1.0, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads if g is not None]
        if not sq:
            return grads
        global_norm = jnp.sqrt(jnp.sum(jnp.stack(sq)))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [None if g is None else
                (g.astype(jnp.float32) * scale).astype(g.dtype)
                for g in grads]
