"""LBFGS and Rprop optimizers (reference python/paddle/optimizer/
lbfgs.py, rprop.py).

LBFGS is eager-by-nature (it re-evaluates the loss via a closure during
line search), so unlike the functional SGD/Adam family its step() takes
a closure — exactly the reference's API. The two-loop recursion runs on
device arrays; only the strong-Wolfe bracketing logic is host-side
control flow."""
from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS", "Rprop"]


def _flat(arrs):
    return jnp.concatenate([a.reshape(-1).astype(jnp.float32)
                            for a in arrs])


class LBFGS(Optimizer):
    """Limited-memory BFGS with strong-Wolfe line search (reference
    lbfgs.py LBFGS). Usage:

        opt = LBFGS(parameters=model.parameters(), history_size=10)
        def closure():
            opt.clear_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            return loss
        opt.step(closure)
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self.max_iter = max_iter
        self.max_eval = max_eval or max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s: List = []
        self._y: List = []

    def _gather(self):
        params = self._parameter_list
        x = _flat([p._value for p in params])
        grads = [p.grad._value if p.grad is not None
                 else jnp.zeros_like(p._value) for p in params]
        # honor the base-class args every other optimizer applies
        if self._grad_clip is not None:
            grads = self._grad_clip.apply(grads)
        if self._weight_decay:
            from .optimizer import _wd_grad
            grads = [_wd_grad(p._value, g, self._weight_decay)
                     for p, g in zip(params, grads)]
        return x, _flat(grads)

    def _scatter(self, x):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p._value.shape))
            p._replace(x[off:off + n].reshape(p._value.shape).astype(
                p._value.dtype))
            off += n

    def _direction(self, g):
        """Two-loop recursion over the (s, y) history."""
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._y:
            y_last, s_last = self._y[-1], self._s[-1]
            gamma = jnp.vdot(s_last, y_last) / jnp.maximum(
                jnp.vdot(y_last, y_last), 1e-10)
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        return -q

    def step(self, closure: Callable):
        loss = closure()
        x, g = self._gather()
        if float(jnp.abs(g).max()) <= self.tol_grad:
            return loss
        evals = 1
        for _ in range(self.max_iter):
            d = self._direction(g)
            t = float(self._learning_rate) if not self._s else 1.0
            gtd = float(jnp.vdot(g, d))
            if gtd > -1e-15:  # not a descent direction: reset memory
                self._s.clear()
                self._y.clear()
                d = -g
                gtd = float(jnp.vdot(g, d))

            f0 = float(loss.numpy() if isinstance(loss, Tensor) else loss)
            if self.line_search_fn is None:
                # reference semantics: no search — one fixed-lr step
                t = float(self._learning_rate)
                self._scatter(x + t * d)
                loss_new = closure()
                evals += 1
            elif self.line_search_fn == "strong_wolfe":
                success = False
                for _ls in range(20):
                    self._scatter(x + t * d)
                    loss_new = closure()
                    evals += 1
                    f1 = float(loss_new.numpy()
                               if isinstance(loss_new, Tensor)
                               else loss_new)
                    if f1 <= f0 + 1e-4 * t * gtd:  # Armijo
                        _, g_new = self._gather()
                        if abs(float(jnp.vdot(g_new, d))) <= \
                                0.9 * abs(gtd):  # curvature
                            success = True
                            break
                        t *= 1.5 if float(jnp.vdot(g_new, d)) < 0 else 0.5
                        continue
                    t *= 0.5
                if not success:
                    self._scatter(x)  # restore
                    return loss
            else:
                raise ValueError(
                    f"unsupported line_search_fn "
                    f"{self.line_search_fn!r}; use None or "
                    f"'strong_wolfe'")
            x_new, g_new = self._gather()
            s = x_new - x
            ygap = g_new - g
            if float(jnp.vdot(s, ygap)) > 1e-10:
                self._s.append(s)
                self._y.append(ygap)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            delta = float(jnp.abs(s).max())
            x, g, loss = x_new, g_new, loss_new
            if delta < self.tol_change or \
                    float(jnp.abs(g).max()) <= self.tol_grad or \
                    evals >= self.max_eval:
                break
        return loss


class Rprop(Optimizer):
    """Resilient backprop (reference rprop.py): per-weight step sizes
    grown/shrunk by gradient sign agreement; gradients' magnitudes are
    ignored. Full-batch method, per the reference docs."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision=multi_precision)
        self.lr_min, self.lr_max = learning_rate_range
        self.eta_neg, self.eta_pos = etas

    def _init_state_impl(self, params):
        lr0 = float(self._learning_rate) if not callable(
            getattr(self._learning_rate, "get_lr", None)) else \
            self._learning_rate.get_lr()
        return {
            "step_size": [jnp.full(p.shape, lr0, jnp.float32)
                          for p in params],
            "prev_grad": [jnp.zeros(p.shape, jnp.float32)
                          for p in params],
        }

    def _update_impl(self, params, grads, state, lr):
        new_p, new_sz, new_pg = [], [], []
        for p, g, sz, pg in zip(params, grads, state["step_size"],
                                state["prev_grad"]):
            if g is None:
                new_p.append(None)
                new_sz.append(sz)
                new_pg.append(pg)
                continue
            g = g.astype(jnp.float32)
            sign = jnp.sign(g * pg)
            sz2 = jnp.clip(
                jnp.where(sign > 0, sz * self.eta_pos,
                          jnp.where(sign < 0, sz * self.eta_neg, sz)),
                self.lr_min, self.lr_max)
            # on sign flip: no step, zero the remembered grad
            g_eff = jnp.where(sign < 0, 0.0, g)
            step = sz2 * jnp.sign(g_eff)
            new_p.append((p.astype(jnp.float32) - step).astype(p.dtype))
            new_sz.append(sz2)
            new_pg.append(g_eff)
        return new_p, {"step_size": new_sz, "prev_grad": new_pg}
