"""Optimizer base + family (parity:
/root/reference/python/paddle/optimizer/optimizer.py:103).

Design: every optimizer is defined by a *functional core* —
``init_state(params) -> state`` and ``update(params, grads, state, lr) ->
(new_params, new_state)`` over raw jax arrays. The eager ``step()`` (paddle
API) wraps the core over ``param.grad``; the jitted train-step path
(paddle_tpu.jit.TrainStep) calls the same core inside jax.jit, so numerics
are identical and there is exactly one implementation of each rule.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor, no_grad
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision: bool = True):
        if parameters is None:
            raise ValueError("parameters must be provided (list of Parameter)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._multi_precision = multi_precision
        self._state: Optional[Dict[str, Any]] = None
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("set_lr not allowed with an LRScheduler")
        self._learning_rate = float(value)

    # -- functional core (subclasses implement _update_impl) ----------------
    def init_state(self, params: List[jax.Array]) -> Dict[str, Any]:
        return self._with_master(self._init_state_impl(params), params)

    def _init_state_impl(self, params) -> Dict[str, Any]:
        return {"step": jnp.zeros((), jnp.int32)}

    def update(self, params: List[jax.Array], grads: List[Optional[jax.Array]],
               state: Dict[str, Any], lr) -> tuple:
        """Template: route low-precision params through their persistent
        float32 master copies (kept in state['master'], like the reference
        AMP-O2 optimizer's master weights), run the subclass rule in f32,
        then cast results back to the storage dtype."""
        masters = state.get("master")
        if masters is None:
            return self._update_impl(params, grads, state, lr)
        eff = [masters[i] if masters[i] is not None else p
               for i, p in enumerate(params)]
        new_eff, new_state = self._update_impl(eff, grads, state, lr)
        new_params, new_masters = [], []
        for i, (p, ne) in enumerate(zip(params, new_eff)):
            if ne is None:
                new_params.append(None)
                new_masters.append(masters[i])
                continue
            if masters[i] is not None:
                new_masters.append(ne)
                new_params.append(ne.astype(p.dtype))
            else:
                new_masters.append(None)
                new_params.append(ne)
        new_state["master"] = new_masters
        return new_params, new_state

    def _update_impl(self, params, grads, state, lr) -> tuple:
        raise NotImplementedError

    # -- shared helpers ------------------------------------------------------
    def _needs_master(self, p) -> bool:
        return self._multi_precision and p.dtype in (jnp.bfloat16, jnp.float16)

    def _with_master(self, st: Dict[str, Any], params) -> Dict[str, Any]:
        if any(self._needs_master(p) for p in params):
            st["master"] = [p.astype(jnp.float32) if self._needs_master(p)
                            else None for p in params]
        return st

    def _master(self, p):
        """float32 view for state init / stateless rules (persistent master
        copies live in state['master'], handled by the update template)."""
        if self._multi_precision and p.dtype in (jnp.bfloat16, jnp.float16):
            return p.astype(jnp.float32)
        return p

    def _apply_clip_and_decay(self, params, grads):
        if self._grad_clip is not None:
            grads = self._grad_clip.apply(grads)
        return grads

    # -- eager API -----------------------------------------------------------
    def step(self):
        # advance the numerics-checker's debug_step window, if active
        from ..amp import debugging as _dbg
        if _dbg._checker is not None:
            _dbg._checker.step()
        params = self._parameter_list
        raw_params = [p._value for p in params]
        raw_grads = [None if p.grad is None else p.grad._value for p in params]
        if self._state is None:
            self._state = self.init_state(raw_params)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        new_params, self._state = self.update(raw_params, raw_grads,
                                              self._state, lr)
        for p, np_ in zip(params, new_params):
            if np_ is not None:
                p._replace(np_)
        self._step_count += 1

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # static mode: attach the train spec to the loss's Program — the
        # Executor compiles fwd+bwd+update as one donated-buffer XLA step
        # (reference: minimize appends backward+optimizer OpDescs,
        # python/paddle/optimizer/optimizer.py)
        from ..static.program import Variable as _StaticVar
        if isinstance(loss, _StaticVar):
            loss.program._train_spec = {"loss": loss, "optimizer": self}
            return [], []
        loss.backward()
        self.step()
        self.clear_grad()

    # -- state dict ----------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        sd = {"step_count": self._step_count}
        if self._state is not None:
            sd["state"] = jax.tree_util.tree_map(np.asarray, self._state)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]):
        self._step_count = state_dict.get("step_count", 0)
        if "state" in state_dict:
            self._state = jax.tree_util.tree_map(jnp.asarray,
                                                 state_dict["state"])
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])


def _wd_grad(p, g, wd):
    """Couple weight decay into the gradient (paddle regularizer style).
    wd may be a float coefficient or a paddle.regularizer L1Decay/L2Decay
    object (reference: regularizer applied at grad time)."""
    if g is None or not wd:
        return g
    from ..regularizer import L1Decay, WeightDecayRegularizer
    if isinstance(wd, WeightDecayRegularizer):
        return wd.apply_to_grad(p.astype(g.dtype), g)
    return g + wd * p.astype(g.dtype)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32)}

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        new_params = []
        for p, g in zip(params, grads):
            if g is None:
                new_params.append(None)
                continue
            g = _wd_grad(p, g, self._weight_decay)
            m = self._master(p)
            m = m - lr.astype(m.dtype) * g.astype(m.dtype)
            new_params.append(m.astype(p.dtype))
        return new_params, {"step": state["step"] + 1}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "velocity": [jnp.zeros_like(self._master(p)) for p in params]}

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        mu = self._momentum
        new_params, new_vel = [], []
        for p, g, v in zip(params, grads, state["velocity"]):
            if g is None:
                new_params.append(None)
                new_vel.append(v)
                continue
            g = _wd_grad(p, g, self._weight_decay)
            m = self._master(p)
            g32 = g.astype(m.dtype)
            v = mu * v + g32
            if self._nesterov:
                upd = g32 + mu * v
            else:
                upd = v
            m = m - lr.astype(m.dtype) * upd
            new_params.append(m.astype(p.dtype))
            new_vel.append(v)
        return new_params, {"step": state["step"] + 1, "velocity": new_vel}


class Adam(Optimizer):
    _decoupled_wd = False

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 name=None, amsgrad=False, moment_dtype=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        # moment_dtype: storage dtype for m/v (None = master dtype).
        # 'bfloat16' halves optimizer-state HBM — the arithmetic stays
        # f32 (moments cast up on read, down on write), so only the
        # STORED moments are rounded. On a 16 GB chip this is what lets
        # a ~1B AdamW model trade remat for stored activations.
        self._moment_dtype = None if moment_dtype is None else \
            jnp.dtype(moment_dtype) if not isinstance(moment_dtype, str) \
            else {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                  "float16": jnp.float16}[moment_dtype]

    def _moment_zeros(self, p):
        # zeros_like: the moment inherits the master's SHARDING (a
        # plain zeros would replicate sharded optimizer state)
        mp = self._master(p)
        return jnp.zeros_like(mp, dtype=self._moment_dtype or mp.dtype)

    def _init_state_impl(self, params):
        st = {"step": jnp.zeros((), jnp.int32),
              "m": [self._moment_zeros(p) for p in params],
              "v": [self._moment_zeros(p) for p in params]}
        if self._amsgrad:
            st["vmax"] = [self._moment_zeros(p) for p in params]
        return st

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = state["step"] + 1
        tf = t.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(b1, tf)
        bc2 = 1.0 - jnp.power(b2, tf)
        new_params, new_m, new_v = [], [], []
        new_vmax = [] if self._amsgrad else None
        for i, (p, g) in enumerate(zip(params, grads)):
            m_s, v_s = state["m"][i], state["v"][i]
            if g is None:
                new_params.append(None)
                new_m.append(m_s)
                new_v.append(v_s)
                if self._amsgrad:
                    new_vmax.append(state["vmax"][i])
                continue
            mp = self._master(p)
            if not self._decoupled_wd:
                g = _wd_grad(p, g, self._weight_decay)
            g32 = g.astype(mp.dtype)
            store_dt = m_s.dtype
            m_s = b1 * m_s.astype(g32.dtype) + (1 - b1) * g32
            v_s = b2 * v_s.astype(g32.dtype) + (1 - b2) * jnp.square(g32)
            m_hat = m_s / bc1
            v_hat = v_s / bc2
            if self._amsgrad:
                vm = jnp.maximum(state["vmax"][i].astype(g32.dtype),
                                 v_hat)
                new_vmax.append(vm.astype(store_dt))
                denom = jnp.sqrt(vm) + eps
            else:
                denom = jnp.sqrt(v_hat) + eps
            upd = m_hat / denom
            if self._decoupled_wd and self._weight_decay:
                wd = self._weight_decay
                from ..regularizer import L1Decay, WeightDecayRegularizer
                if isinstance(wd, L1Decay):
                    raise NotImplementedError(
                        "L1Decay has no decoupled (AdamW-style) form; "
                        "use a coupled optimizer (SGD/Momentum/Adam) "
                        "for L1 regularization")
                if isinstance(wd, WeightDecayRegularizer):
                    wd = wd.coeff  # L2: decoupled uses the coefficient
                mp = mp * (1.0 - lr.astype(mp.dtype) * wd)
            mp = mp - lr.astype(mp.dtype) * upd
            new_params.append(mp.astype(p.dtype))
            new_m.append(m_s.astype(store_dt))
            new_v.append(v_s.astype(store_dt))
        out_state = {"step": t, "m": new_m, "v": new_v}
        if self._amsgrad:
            out_state["vmax"] = new_vmax
        return new_params, out_state


class AdamW(Adam):
    """Decoupled weight decay (paddle.optimizer.AdamW,
    /root/reference/python/paddle/optimizer/adamw.py)."""
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None,
                 amsgrad=False, moment_dtype=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, amsgrad, moment_dtype=moment_dtype)
        self._apply_decay_param_fun = apply_decay_param_fun
        # static per-param decay mask (True = apply decay), from param names
        if apply_decay_param_fun is not None:
            self._decay_mask = [bool(apply_decay_param_fun(p.name))
                                for p in self._parameter_list]
        else:
            self._decay_mask = [True] * len(self._parameter_list)

    def update(self, params, grads, state, lr):
        saved_wd = self._weight_decay
        if not all(self._decay_mask):
            # per-param decay: run the shared Adam core param-by-param with
            # wd toggled; cheap because lists are short-lived python
            new_params, new_state = [], None
            for i in range(len(params)):
                self._weight_decay = saved_wd if self._decay_mask[i] else 0.0
                sub_state = {k: (v if not isinstance(v, list) else [v[i]])
                             for k, v in state.items()}
                ps, st = super().update([params[i]], [grads[i]], sub_state, lr)
                new_params.append(ps[0])
                if new_state is None:
                    new_state = {k: (v if not isinstance(v, list) else list(v))
                                 for k, v in st.items()}
                else:
                    for k, v in st.items():
                        if isinstance(v, list):
                            new_state[k].append(v[0])
                        else:
                            new_state[k] = v
            self._weight_decay = saved_wd
            return new_params, new_state
        return super().update(params, grads, state, lr)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": [jnp.zeros_like(self._master(p)) for p in params],
                "u": [jnp.zeros_like(self._master(p)) for p in params]}

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = state["step"] + 1
        bc1 = 1.0 - jnp.power(b1, t.astype(jnp.float32))
        new_params, new_m, new_u = [], [], []
        for p, g, m_s, u_s in zip(params, grads, state["m"], state["u"]):
            if g is None:
                new_params.append(None)
                new_m.append(m_s)
                new_u.append(u_s)
                continue
            g = _wd_grad(p, g, self._weight_decay)
            mp = self._master(p)
            g32 = g.astype(mp.dtype)
            m_s = b1 * m_s + (1 - b1) * g32
            u_s = jnp.maximum(b2 * u_s, jnp.abs(g32))
            mp = mp - lr.astype(mp.dtype) * m_s / (bc1 * (u_s + eps))
            new_params.append(mp.astype(p.dtype))
            new_m.append(m_s)
            new_u.append(u_s)
        return new_params, {"step": t, "m": new_m, "u": new_u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "acc": [jnp.full_like(self._master(p), self._init_acc)
                        for p in params]}

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        new_params, new_acc = [], []
        for p, g, a in zip(params, grads, state["acc"]):
            if g is None:
                new_params.append(None)
                new_acc.append(a)
                continue
            g = _wd_grad(p, g, self._weight_decay)
            mp = self._master(p)
            g32 = g.astype(mp.dtype)
            a = a + jnp.square(g32)
            mp = mp - lr.astype(mp.dtype) * g32 / (jnp.sqrt(a) + self._epsilon)
            new_params.append(mp.astype(p.dtype))
            new_acc.append(a)
        return new_params, {"step": state["step"] + 1, "acc": new_acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon, self._rho = epsilon, rho

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "avg_sq_grad": [jnp.zeros_like(self._master(p)) for p in params],
                "avg_sq_upd": [jnp.zeros_like(self._master(p)) for p in params]}

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        rho, eps = self._rho, self._epsilon
        new_params, new_g2, new_u2 = [], [], []
        for p, g, g2, u2 in zip(params, grads, state["avg_sq_grad"],
                                state["avg_sq_upd"]):
            if g is None:
                new_params.append(None)
                new_g2.append(g2)
                new_u2.append(u2)
                continue
            g = _wd_grad(p, g, self._weight_decay)
            mp = self._master(p)
            g32 = g.astype(mp.dtype)
            g2 = rho * g2 + (1 - rho) * jnp.square(g32)
            upd = jnp.sqrt(u2 + eps) / jnp.sqrt(g2 + eps) * g32
            u2 = rho * u2 + (1 - rho) * jnp.square(upd)
            mp = mp - lr.astype(mp.dtype) * upd
            new_params.append(mp.astype(p.dtype))
            new_g2.append(g2)
            new_u2.append(u2)
        return new_params, {"step": state["step"] + 1,
                            "avg_sq_grad": new_g2, "avg_sq_upd": new_u2}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state_impl(self, params):
        st = {"step": jnp.zeros((), jnp.int32),
              "ms": [jnp.zeros_like(self._master(p)) for p in params],
              "mom": [jnp.zeros_like(self._master(p)) for p in params]}
        if self._centered:
            st["mg"] = [jnp.zeros_like(self._master(p)) for p in params]
        return st

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        new_params, new_ms, new_mom = [], [], []
        new_mg = [] if self._centered else None
        for i, (p, g) in enumerate(zip(params, grads)):
            ms, mom = state["ms"][i], state["mom"][i]
            if g is None:
                new_params.append(None)
                new_ms.append(ms)
                new_mom.append(mom)
                if self._centered:
                    new_mg.append(state["mg"][i])
                continue
            g = _wd_grad(p, g, self._weight_decay)
            mp = self._master(p)
            g32 = g.astype(mp.dtype)
            ms = rho * ms + (1 - rho) * jnp.square(g32)
            if self._centered:
                mg = rho * state["mg"][i] + (1 - rho) * g32
                new_mg.append(mg)
                denom = jnp.sqrt(ms - jnp.square(mg) + eps)
            else:
                denom = jnp.sqrt(ms + eps)
            mom = mu * mom + lr.astype(mp.dtype) * g32 / denom
            mp = mp - mom
            new_params.append(mp.astype(p.dtype))
            new_ms.append(ms)
            new_mom.append(mom)
        st = {"step": state["step"] + 1, "ms": new_ms, "mom": new_mom}
        if self._centered:
            st["mg"] = new_mg
        return new_params, st


class Lamb(Optimizer):
    """Layer-wise adaptive moments (paddle.optimizer.Lamb,
    /root/reference/python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, multi_precision=True):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state_impl(self, params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": [jnp.zeros_like(self._master(p)) for p in params],
                "v": [jnp.zeros_like(self._master(p)) for p in params]}

    def _trust_norm_source(self, mp, p):
        """Array the layer-wise trust ratio norms are taken over
        (DistributedFusedLamb's use_master_param_norm=False overrides
        this to use the low-precision weights)."""
        return mp

    def _update_impl(self, params, grads, state, lr):
        grads = self._apply_clip_and_decay(params, grads)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = state["step"] + 1
        tf = t.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(b1, tf)
        bc2 = 1.0 - jnp.power(b2, tf)
        new_params, new_m, new_v = [], [], []
        for i, (p, g) in enumerate(zip(params, grads)):
            m_s, v_s = state["m"][i], state["v"][i]
            if g is None:
                new_params.append(None)
                new_m.append(m_s)
                new_v.append(v_s)
                continue
            mp = self._master(p)
            g32 = g.astype(mp.dtype)
            m_s = b1 * m_s + (1 - b1) * g32
            v_s = b2 * v_s + (1 - b2) * jnp.square(g32)
            r = (m_s / bc1) / (jnp.sqrt(v_s / bc2) + eps)
            wd = self._weight_decay
            if self._exclude_fn is not None and self._exclude_fn(
                    self._parameter_list[i]):
                wd = 0.0
            r = r + wd * mp
            nsrc = self._trust_norm_source(mp, p)
            w_norm = jnp.sqrt(jnp.sum(jnp.square(nsrc)))
            r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
            trust = jnp.where((w_norm > 0) & (r_norm > 0),
                              w_norm / r_norm, 1.0)
            mp = mp - lr.astype(mp.dtype) * trust * r
            new_params.append(mp.astype(p.dtype))
            new_m.append(m_s)
            new_v.append(v_s)
        return new_params, {"step": t, "m": new_m, "v": new_v}
