// Host event tracer — native low-overhead span recorder for the profiler.
//
// TPU-native analog of the reference's HostTracer
// (/root/reference/paddle/fluid/platform/profiler/host_tracer.h and
// RecordEvent spans in event_tracing.h): paddle_tpu.profiler.RecordEvent
// calls land here as two clock reads + a lock-free ring write (~40ns),
// instead of Python-side dict appends. The Python layer drains the buffer
// and merges spans with the device trace (jax.profiler) into one Chrome
// trace. Device-side tracing itself belongs to XLA/xprof (SURVEY.md §5.1).
//
// Name strings are interned once (pt_trace_intern) so the hot path records
// only integer ids.

#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint32_t tid;
  uint64_t t_start_ns;
  uint64_t t_end_ns;
};

struct Tracer {
  std::vector<Event> ring;
  std::atomic<uint64_t> cursor{0};  // total events written
  std::atomic<bool> enabled{false};

  std::mutex names_mu;
  std::vector<std::string> names;

  explicit Tracer(size_t capacity) : ring(capacity) {}
};

inline uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

inline uint32_t tid() { return (uint32_t)syscall(SYS_gettid); }

}  // namespace

extern "C" {

void* pt_trace_create(uint64_t capacity) {
  return new Tracer(capacity ? capacity : (1u << 20));
}

void pt_trace_destroy(void* h) { delete (Tracer*)h; }

void pt_trace_enable(void* h, int on) {
  ((Tracer*)h)->enabled.store(on != 0, std::memory_order_release);
}

int pt_trace_enabled(void* h) {
  return ((Tracer*)h)->enabled.load(std::memory_order_acquire) ? 1 : 0;
}

uint32_t pt_trace_intern(void* h, const char* name) {
  auto* t = (Tracer*)h;
  std::lock_guard<std::mutex> lk(t->names_mu);
  for (uint32_t i = 0; i < t->names.size(); ++i)
    if (t->names[i] == name) return i;
  t->names.emplace_back(name);
  return (uint32_t)t->names.size() - 1;
}

uint64_t pt_trace_now_ns() { return now_ns(); }

// Record a completed span.
void pt_trace_span(void* h, uint32_t name_id, uint64_t t_start_ns,
                   uint64_t t_end_ns) {
  auto* t = (Tracer*)h;
  if (!t->enabled.load(std::memory_order_acquire)) return;
  uint64_t i = t->cursor.fetch_add(1, std::memory_order_acq_rel);
  Event& e = t->ring[i % t->ring.size()];
  e.name_id = name_id;
  e.tid = tid();
  e.t_start_ns = t_start_ns;
  e.t_end_ns = t_end_ns;
}

// Begin/end convenience (end computes duration itself).
uint64_t pt_trace_begin(void* h) { return now_ns(); }

void pt_trace_end(void* h, uint32_t name_id, uint64_t t_start_ns) {
  pt_trace_span(h, name_id, t_start_ns, now_ns());
}

uint64_t pt_trace_count(void* h) {
  auto* t = (Tracer*)h;
  uint64_t n = t->cursor.load(std::memory_order_acquire);
  return n < t->ring.size() ? n : t->ring.size();
}

uint64_t pt_trace_dropped(void* h) {
  auto* t = (Tracer*)h;
  uint64_t n = t->cursor.load(std::memory_order_acquire);
  return n > t->ring.size() ? n - t->ring.size() : 0;
}

// Drain events into caller-provided parallel arrays (capacity `cap`).
// Returns number of events copied; resets the buffer.
uint64_t pt_trace_drain(void* h, uint32_t* name_ids, uint32_t* tids,
                        uint64_t* starts, uint64_t* ends, uint64_t cap) {
  auto* t = (Tracer*)h;
  uint64_t total = t->cursor.exchange(0, std::memory_order_acq_rel);
  uint64_t n = total < t->ring.size() ? total : t->ring.size();
  if (n > cap) n = cap;
  // oldest-first when wrapped
  uint64_t begin = total > t->ring.size() ? total - t->ring.size() : 0;
  for (uint64_t k = 0; k < n; ++k) {
    const Event& e = t->ring[(begin + k) % t->ring.size()];
    name_ids[k] = e.name_id;
    tids[k] = e.tid;
    starts[k] = e.t_start_ns;
    ends[k] = e.t_end_ns;
  }
  return n;
}

// Copy interned name `i` into buf (cap bytes incl. NUL). Returns full length.
uint32_t pt_trace_name(void* h, uint32_t i, char* buf, uint32_t cap) {
  auto* t = (Tracer*)h;
  std::lock_guard<std::mutex> lk(t->names_mu);
  if (i >= t->names.size()) return 0;
  const std::string& s = t->names[i];
  if (cap) {
    uint32_t n = (uint32_t)s.size() < cap - 1 ? (uint32_t)s.size() : cap - 1;
    std::memcpy(buf, s.data(), n);
    buf[n] = '\0';
  }
  return (uint32_t)s.size();
}

}  // extern "C"
