// TCP key-value rendezvous store — the native bootstrap service.
//
// TPU-native re-imagination of the reference's TCPStore
// (/root/reference/paddle/phi/core/distributed/store/tcp_store.h:121 and
// socket-level MasterDaemon in tcp_utils): rank 0 hosts a small TCP server
// holding a byte-keyed map; every rank (including 0) connects as a client.
// Used by paddle_tpu.distributed.launch for master rendezvous and by
// init_parallel_env as the coordination KV (the jax.distributed service
// covers in-program collectives; this covers host-side orchestration:
// barriers, address exchange, elastic heartbeats).
//
// Exposed as a C ABI consumed from Python via ctypes (no pybind11 in the
// image). All calls are blocking with millisecond timeouts.
//
// Wire protocol (little-endian):
//   request : u8 op | u32 klen | key bytes | u32 vlen | value bytes
//   response: i64 status/num  | u32 vlen | value bytes
// ops: 1=SET 2=GET(blocking till key exists or timeout) 3=ADD(i64 delta,
//      returns new value) 4=CHECK(returns 1/0) 5=DELETE 6=NUM_KEYS
//      7=COMPARE_SET(old new)

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMaxKeyLen = 1u << 16;   // 64 KiB keys
constexpr uint32_t kMaxValLen = 1u << 28;   // 256 MiB values
constexpr int64_t kStatusTooLarge = -3;     // frame exceeded the caps
constexpr int64_t kStatusMalformed = -4;    // bad op-specific encoding

enum Op : uint8_t {
  kSet = 1,
  kGet = 2,
  kAdd = 3,
  kCheck = 4,
  kDelete = 5,
  kNumKeys = 6,
  kCompareSet = 7,
};

struct Daemon {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> data;

  ~Daemon() { Shutdown(); }

  void Shutdown() {
    bool expected = false;
    if (!stop.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
      listen_fd = -1;
    }
    cv.notify_all();
    if (accept_thread.joinable()) accept_thread.join();
    for (auto& w : workers)
      if (w.joinable()) w.join();
  }
};

bool ReadFull(int fd, void* buf, size_t n, int timeout_ms) {
  auto* p = static_cast<uint8_t*>(buf);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (n > 0) {
    struct pollfd pfd = {fd, POLLIN, 0};
    int remain = timeout_ms <= 0
                     ? -1
                     : (int)std::chrono::duration_cast<std::chrono::milliseconds>(
                           deadline - std::chrono::steady_clock::now())
                           .count();
    if (timeout_ms > 0 && remain <= 0) return false;
    int pr = ::poll(&pfd, 1, remain);
    if (pr <= 0) return false;
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool DrainN(int fd, size_t n, int timeout_ms) {
  uint8_t scratch[4096];
  while (n > 0) {
    size_t chunk = n < sizeof(scratch) ? n : sizeof(scratch);
    if (!ReadFull(fd, scratch, chunk, timeout_ms)) return false;
    n -= chunk;
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= (size_t)w;
  }
  return true;
}

void ServeClient(Daemon* d, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (!d->stop.load()) {
    uint8_t op;
    if (!ReadFull(fd, &op, 1, 0)) break;
    uint32_t klen;
    if (!ReadFull(fd, &klen, 4, 10000)) break;
    // Bound allocations. Oversized KEYS (any op) are a protocol
    // violation — legit keys are short strings — so the connection is
    // dropped. An oversized VALUE on kSet/kCompareSet (the two ops that
    // legitimately carry big payloads and whose status field is a pure
    // status) gets drained and answered with kStatusTooLarge so the
    // shared client handle survives; on value-carrying ops (kAdd etc.)
    // the status field is the return value, so -3 would be ambiguous —
    // those frames also drop the connection.
    if (klen > kMaxKeyLen) break;
    std::string key(klen, '\0');
    if (klen && !ReadFull(fd, key.data(), klen, 10000)) break;
    uint32_t vlen;
    if (!ReadFull(fd, &vlen, 4, 10000)) break;
    std::vector<uint8_t> val;
    if (vlen > kMaxValLen) {
      if (op != kSet && op != kCompareSet) break;
      if (!DrainN(fd, vlen, 10000)) break;
      int64_t status = kStatusTooLarge;
      uint32_t zero = 0;
      uint8_t hdr[12];
      std::memcpy(hdr, &status, 8);
      std::memcpy(hdr + 8, &zero, 4);
      if (!WriteFull(fd, hdr, 12)) break;
      continue;
    }
    val.resize(vlen);
    if (vlen && !ReadFull(fd, val.data(), vlen, 10000)) break;

    int64_t status = 0;
    std::vector<uint8_t> out;
    switch (op) {
      case kSet: {
        std::lock_guard<std::mutex> lk(d->mu);
        d->data[key] = std::move(val);
        d->cv.notify_all();
        break;
      }
      case kGet: {
        // value holds i64 timeout_ms (0 = wait forever)
        int64_t tmo = 0;
        if (val.size() >= 8) std::memcpy(&tmo, val.data(), 8);
        std::unique_lock<std::mutex> lk(d->mu);
        auto pred = [&] { return d->stop.load() || d->data.count(key); };
        bool ok;
        if (tmo > 0)
          ok = d->cv.wait_for(lk, std::chrono::milliseconds(tmo), pred);
        else {
          d->cv.wait(lk, pred);
          ok = true;
        }
        if (ok && d->data.count(key)) {
          out = d->data[key];
        } else {
          status = -1;  // timeout
        }
        break;
      }
      case kAdd: {
        int64_t delta = 0;
        if (val.size() >= 8) std::memcpy(&delta, val.data(), 8);
        std::lock_guard<std::mutex> lk(d->mu);
        int64_t cur = 0;
        auto it = d->data.find(key);
        if (it != d->data.end() && it->second.size() == 8)
          std::memcpy(&cur, it->second.data(), 8);
        cur += delta;
        std::vector<uint8_t> nv(8);
        std::memcpy(nv.data(), &cur, 8);
        d->data[key] = nv;
        status = cur;
        d->cv.notify_all();
        break;
      }
      case kCheck: {
        std::lock_guard<std::mutex> lk(d->mu);
        status = d->data.count(key) ? 1 : 0;
        break;
      }
      case kDelete: {
        std::lock_guard<std::mutex> lk(d->mu);
        status = d->data.erase(key);
        d->cv.notify_all();
        break;
      }
      case kNumKeys: {
        std::lock_guard<std::mutex> lk(d->mu);
        status = (int64_t)d->data.size();
        break;
      }
      case kCompareSet: {
        // val = u32 oldlen | old | new — reject malformed frames instead of
        // slicing past the end (hostile/corrupt clients must not crash the
        // rendezvous master).
        uint32_t olen = 0;
        if (val.size() < 4) {
          status = kStatusMalformed;
          break;
        }
        std::memcpy(&olen, val.data(), 4);
        if ((size_t)olen > val.size() - 4) {
          status = kStatusMalformed;
          break;
        }
        std::vector<uint8_t> oldv(val.begin() + 4, val.begin() + 4 + olen);
        std::vector<uint8_t> newv(val.begin() + 4 + olen, val.end());
        std::lock_guard<std::mutex> lk(d->mu);
        auto it = d->data.find(key);
        if ((it == d->data.end() && oldv.empty()) ||
            (it != d->data.end() && it->second == oldv)) {
          d->data[key] = newv;
          status = 1;
          out = newv;
          d->cv.notify_all();
        } else {
          status = 0;
          if (it != d->data.end()) out = it->second;
        }
        break;
      }
      default:
        status = -2;
    }
    uint32_t olen = (uint32_t)out.size();
    uint8_t hdr[12];
    std::memcpy(hdr, &status, 8);
    std::memcpy(hdr + 8, &olen, 4);
    if (!WriteFull(fd, hdr, 12)) break;
    if (olen && !WriteFull(fd, out.data(), olen)) break;
  }
  ::close(fd);
}

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight per client handle
};

}  // namespace

extern "C" {

// ---- server ----
void* pt_kv_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  // Default INADDR_ANY (multi-host rendezvous needs it); deployments on
  // untrusted networks can pin the listen interface via PT_KV_BIND_ADDR
  // (e.g. "127.0.0.1" for single-host runs). The store carries pickled
  // control-plane envelopes and MUST only be reachable from the trusted
  // pod network — same trust model as the reference TCPStore.
  addr.sin_addr.s_addr = INADDR_ANY;
  if (const char* bind_addr = ::getenv("PT_KV_BIND_ADDR")) {
    in_addr parsed{};
    if (::inet_pton(AF_INET, bind_addr, &parsed) != 1) {
      // Fail closed: a typo'd bind address must not silently fall back
      // to listening on every interface.
      std::fprintf(stderr,
                   "paddle_tpu kv_store: PT_KV_BIND_ADDR=%s is not a "
                   "valid IPv4 dotted-quad address; refusing to start\n",
                   bind_addr);
      ::close(fd);
      return nullptr;
    }
    addr.sin_addr = parsed;
  }
  addr.sin_port = htons((uint16_t)port);
  if (::bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, (sockaddr*)&addr, &alen);
  auto* d = new Daemon();
  d->listen_fd = fd;
  d->port = ntohs(addr.sin_port);
  d->accept_thread = std::thread([d] {
    while (!d->stop.load()) {
      int cfd = ::accept(d->listen_fd, nullptr, nullptr);
      if (cfd < 0) {
        if (d->stop.load()) break;
        continue;
      }
      d->workers.emplace_back(ServeClient, d, cfd);
    }
  });
  return d;
}

int pt_kv_server_port(void* h) { return h ? ((Daemon*)h)->port : -1; }

void pt_kv_server_stop(void* h) {
  if (!h) return;
  auto* d = (Daemon*)h;
  d->Shutdown();
  delete d;
}

// ---- client ----
void* pt_kv_connect(const char* host, int port, int timeout_ms) {
  struct addrinfo hints {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  char ports[16];
  snprintf(ports, sizeof(ports), "%d", port);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 60000);
  while (std::chrono::steady_clock::now() < deadline) {
    if (getaddrinfo(host, ports, &hints, &res) == 0) {
      int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 &&
          ::connect(fd, res->ai_addr, (socklen_t)res->ai_addrlen) == 0) {
        freeaddrinfo(res);
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        auto* c = new Client();
        c->fd = fd;
        return c;
      }
      if (fd >= 0) ::close(fd);
      freeaddrinfo(res);
      res = nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
  return nullptr;
}

void pt_kv_disconnect(void* h) {
  if (!h) return;
  auto* c = (Client*)h;
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

static int64_t Request(Client* c, uint8_t op, const char* key, uint32_t klen,
                       const uint8_t* val, uint32_t vlen, uint8_t** out,
                       uint32_t* out_len) {
  std::lock_guard<std::mutex> lk(c->mu);
  std::vector<uint8_t> req(1 + 4 + klen + 4 + vlen);
  req[0] = op;
  std::memcpy(req.data() + 1, &klen, 4);
  std::memcpy(req.data() + 5, key, klen);
  std::memcpy(req.data() + 5 + klen, &vlen, 4);
  if (vlen) std::memcpy(req.data() + 9 + klen, val, vlen);
  if (!WriteFull(c->fd, req.data(), req.size())) return INT64_MIN;
  uint8_t hdr[12];
  if (!ReadFull(c->fd, hdr, 12, 0)) return INT64_MIN;
  int64_t status;
  uint32_t olen;
  std::memcpy(&status, hdr, 8);
  std::memcpy(&olen, hdr + 8, 4);
  uint8_t* buf = nullptr;
  if (olen) {
    buf = (uint8_t*)malloc(olen);
    if (!ReadFull(c->fd, buf, olen, 0)) {
      free(buf);
      return INT64_MIN;
    }
  }
  if (out) {
    *out = buf;
    *out_len = olen;
  } else {
    free(buf);
  }
  return status;
}

int64_t pt_kv_set(void* h, const char* key, const uint8_t* val, uint32_t vlen) {
  return Request((Client*)h, kSet, key, (uint32_t)strlen(key), val, vlen,
                 nullptr, nullptr);
}

// returns status (0 ok, -1 timeout); *out malloc'd — caller frees via
// pt_kv_free.
int64_t pt_kv_get(void* h, const char* key, int64_t timeout_ms, uint8_t** out,
                  uint32_t* out_len) {
  return Request((Client*)h, kGet, key, (uint32_t)strlen(key),
                 (const uint8_t*)&timeout_ms, 8, out, out_len);
}

int64_t pt_kv_add(void* h, const char* key, int64_t delta) {
  return Request((Client*)h, kAdd, key, (uint32_t)strlen(key),
                 (const uint8_t*)&delta, 8, nullptr, nullptr);
}

int64_t pt_kv_check(void* h, const char* key) {
  return Request((Client*)h, kCheck, key, (uint32_t)strlen(key), nullptr, 0,
                 nullptr, nullptr);
}

int64_t pt_kv_delete(void* h, const char* key) {
  return Request((Client*)h, kDelete, key, (uint32_t)strlen(key), nullptr, 0,
                 nullptr, nullptr);
}

int64_t pt_kv_num_keys(void* h) {
  return Request((Client*)h, kNumKeys, "", 0, nullptr, 0, nullptr, nullptr);
}

int64_t pt_kv_compare_set(void* h, const char* key, const uint8_t* oldv,
                          uint32_t oldlen, const uint8_t* newv,
                          uint32_t newlen) {
  std::vector<uint8_t> val(4 + oldlen + newlen);
  std::memcpy(val.data(), &oldlen, 4);
  if (oldlen) std::memcpy(val.data() + 4, oldv, oldlen);
  if (newlen) std::memcpy(val.data() + 4 + oldlen, newv, newlen);
  return Request((Client*)h, kCompareSet, key, (uint32_t)strlen(key),
                 val.data(), (uint32_t)val.size(), nullptr, nullptr);
}

void pt_kv_free(uint8_t* p) { free(p); }

}  // extern "C"
