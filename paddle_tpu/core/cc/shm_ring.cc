// Shared-memory ring buffer — the native multiprocess data-path.
//
// TPU-native replacement for the reference DataLoader's worker→main
// transport (/root/reference/python/paddle/io/dataloader/worker.py:273
// _worker_loop + multiprocessing queues backed by pickled LoDTensors):
// instead of pickling through a pipe, worker processes serialize batches
// straight into a POSIX shared-memory ring; the main process maps the same
// ring and hands zero-copy views to numpy → jax.device_put. This removes
// one full copy + pickle pass per batch and keeps the host side of the
// input pipeline off the GIL.
//
// Layout:   [Header | slot 0 | slot 1 | ... | slot n-1]
// Each slot: [SlotHeader | payload bytes]
// Single-consumer, multi-producer. Producers claim slots with an atomic
// ticket (head); the consumer reads slots strictly in ticket order (tail),
// which preserves batch ordering per the acquiring order.
// Synchronization: C++11 atomics on lock-free counters + futex-free
// micro-sleep waits (robust to producer death; consumer applies timeouts).

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>

namespace {

constexpr uint32_t kMagic = 0x50545452;  // "PTTR"

enum SlotState : uint32_t {
  kFree = 0,
  kWriting = 1,
  kReady = 2,
  kReading = 3,
};

struct SlotHeader {
  std::atomic<uint32_t> state;
  uint32_t payload_len;
  uint64_t ticket;     // global sequence number of the batch in this slot
  int64_t meta;        // producer-defined (e.g. batch index / sentinel)
};

struct Header {
  uint32_t magic;
  uint32_t n_slots;
  uint64_t slot_bytes;  // payload capacity per slot
  std::atomic<uint64_t> head;  // next ticket to produce
  std::atomic<uint64_t> tail;  // next ticket to consume
  std::atomic<uint32_t> producers_done;  // count of finished producers
  std::atomic<uint32_t> epoch;
  // consumer-published progress (e.g. batches emitted in order) — lets
  // producers throttle so a slow peer can't make the consumer buffer an
  // unbounded reorder backlog.
  std::atomic<uint64_t> progress;
};

struct Ring {
  Header* hdr;
  uint8_t* base;
  size_t total_bytes;
  std::string name;
  bool owner;
};

inline SlotHeader* slot_hdr(Ring* r, uint64_t ticket) {
  uint64_t idx = ticket % r->hdr->n_slots;
  size_t stride = sizeof(SlotHeader) + r->hdr->slot_bytes;
  return reinterpret_cast<SlotHeader*>(r->base + sizeof(Header) +
                                       idx * stride);
}

inline uint8_t* slot_payload(SlotHeader* s) {
  return reinterpret_cast<uint8_t*>(s) + sizeof(SlotHeader);
}

bool wait_state(std::atomic<uint32_t>& a, uint32_t want, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int spins = 0;
  while (a.load(std::memory_order_acquire) != want) {
    if (++spins < 64) {
      std::this_thread::yield();
    } else {
      std::this_thread::sleep_for(std::chrono::microseconds(200));
      if (timeout_ms > 0 && std::chrono::steady_clock::now() > deadline)
        return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring named `name`.
void* pt_ring_open(const char* name, uint64_t slot_bytes, uint32_t n_slots,
                   int create) {
  size_t stride = sizeof(SlotHeader) + slot_bytes;
  size_t total = sizeof(Header) + stride * n_slots;
  int flags = create ? (O_CREAT | O_RDWR) : O_RDWR;
  int fd = ::shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create && ::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    struct stat st;
    if (::fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      ::close(fd);
      return nullptr;
    }
    total = (size_t)st.st_size;
  }
  void* mem = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* r = new Ring();
  r->base = (uint8_t*)mem;
  r->hdr = (Header*)mem;
  r->total_bytes = total;
  r->name = name;
  r->owner = create != 0;
  if (create) {
    std::memset(mem, 0, sizeof(Header));
    r->hdr->magic = kMagic;
    r->hdr->n_slots = n_slots;
    r->hdr->slot_bytes = slot_bytes;
    for (uint32_t i = 0; i < n_slots; ++i)
      slot_hdr(r, i)->state.store(kFree, std::memory_order_relaxed);
  } else if (r->hdr->magic != kMagic) {
    ::munmap(mem, total);
    delete r;
    return nullptr;
  }
  return r;
}

void pt_ring_close(void* h) {
  if (!h) return;
  auto* r = (Ring*)h;
  ::munmap(r->base, r->total_bytes);
  if (r->owner) ::shm_unlink(r->name.c_str());
  delete r;
}

uint64_t pt_ring_slot_bytes(void* h) { return ((Ring*)h)->hdr->slot_bytes; }
uint32_t pt_ring_n_slots(void* h) { return ((Ring*)h)->hdr->n_slots; }

// Producer: claim the next slot for writing. Returns pointer to payload or
// nullptr on timeout. *ticket_out receives the claimed ticket.
//
// A ticket is only claimed (head CAS) once the consumer's tail proves the
// target slot has been released for this wrap (ticket < tail + n_slots).
// The consumer stores kFree before advancing tail, so tail ordering alone
// serializes slot reuse across producers — no producer can observe a stale
// kFree from a previous wrap and clobber a peer. On timeout nothing was
// claimed, so the ring is left fully consistent (no skipped tickets).
uint8_t* pt_ring_acquire_write(void* h, uint64_t* ticket_out, int timeout_ms) {
  auto* r = (Ring*)h;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int spins = 0;
  while (true) {
    uint64_t ticket = r->hdr->head.load(std::memory_order_acquire);
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    if (ticket < tail + r->hdr->n_slots) {
      if (!r->hdr->head.compare_exchange_weak(ticket, ticket + 1,
                                              std::memory_order_acq_rel)) {
        continue;  // lost the claim race; retry with the new head
      }
      SlotHeader* s = slot_hdr(r, ticket);
      s->state.store(kWriting, std::memory_order_release);
      s->ticket = ticket;
      *ticket_out = ticket;
      return slot_payload(s);
    }
    // Ring full: wait for consumer progress.
    if (++spins < 64) {
      std::this_thread::yield();
    } else {
      std::this_thread::sleep_for(std::chrono::microseconds(200));
      if (timeout_ms > 0 && std::chrono::steady_clock::now() > deadline)
        return nullptr;
    }
  }
}

void pt_ring_commit_write(void* h, uint64_t ticket, uint32_t payload_len,
                          int64_t meta) {
  auto* r = (Ring*)h;
  SlotHeader* s = slot_hdr(r, ticket);
  s->payload_len = payload_len;
  s->meta = meta;
  s->state.store(kReady, std::memory_order_release);
}

// Consumer: wait for the next in-order slot to be ready. Returns payload
// pointer (valid until pt_ring_release_read) or nullptr on timeout.
uint8_t* pt_ring_acquire_read(void* h, uint32_t* len_out, int64_t* meta_out,
                              uint64_t* ticket_out, int timeout_ms) {
  auto* r = (Ring*)h;
  uint64_t ticket = r->hdr->tail.load(std::memory_order_acquire);
  SlotHeader* s = slot_hdr(r, ticket);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int spins = 0;
  while (true) {
    uint32_t st = s->state.load(std::memory_order_acquire);
    if (st == kReady && s->ticket == ticket) break;
    if (++spins < 64) {
      std::this_thread::yield();
    } else {
      std::this_thread::sleep_for(std::chrono::microseconds(200));
      if (timeout_ms > 0 && std::chrono::steady_clock::now() > deadline)
        return nullptr;
    }
  }
  s->state.store(kReading, std::memory_order_release);
  *len_out = s->payload_len;
  *meta_out = s->meta;
  *ticket_out = ticket;
  return slot_payload(s);
}

void pt_ring_release_read(void* h, uint64_t ticket) {
  auto* r = (Ring*)h;
  SlotHeader* s = slot_hdr(r, ticket);
  s->state.store(kFree, std::memory_order_release);
  r->hdr->tail.store(ticket + 1, std::memory_order_release);
}

void pt_ring_producer_done(void* h) {
  ((Ring*)h)->hdr->producers_done.fetch_add(1, std::memory_order_acq_rel);
}

uint32_t pt_ring_producers_done(void* h) {
  return ((Ring*)h)->hdr->producers_done.load(std::memory_order_acquire);
}

void pt_ring_set_progress(void* h, uint64_t v) {
  ((Ring*)h)->hdr->progress.store(v, std::memory_order_release);
}

uint64_t pt_ring_progress(void* h) {
  return ((Ring*)h)->hdr->progress.load(std::memory_order_acquire);
}

// Pending = produced-but-not-consumed tickets (approximate, racy by design).
uint64_t pt_ring_pending(void* h) {
  auto* r = (Ring*)h;
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  return head > tail ? head - tail : 0;
}

}  // extern "C"
