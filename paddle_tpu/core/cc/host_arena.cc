// Host staging arena — native pooled allocator for input-pipeline buffers.
//
// TPU-native analog of the reference's host-side allocator strategies
// (/root/reference/paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.h:30
// and the pinned-memory pool): device HBM is owned by the XLA runtime
// (SURVEY.md §2.5 item 7), but the host staging path (batch assembly before
// jax.device_put, checkpoint shard buffers) still benefits from a pooling
// allocator that avoids malloc/mmap churn on multi-MB buffers.
//
// Design: auto-growth best-fit with size-bucketed free lists over mmap'd
// chunks. Free blocks coalesce with neighbors on release. Thread-safe.

#include <sys/mman.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <new>

namespace {

constexpr size_t kAlign = 128;         // TPU-friendly host alignment
constexpr size_t kMinChunk = 8 << 20;  // grow in >=8MB mmap chunks

struct Block {
  size_t size;      // usable bytes (excluding header)
  bool free;
  Block* prev;      // address-ordered neighbors within a chunk
  Block* next;
};

struct Arena {
  std::mutex mu;
  // free blocks keyed by size (multimap: best-fit = lower_bound)
  std::multimap<size_t, Block*> free_blocks;
  size_t total_reserved = 0;
  size_t total_in_use = 0;
  size_t peak_in_use = 0;
  size_t alloc_count = 0;

  void insert_free(Block* b) {
    b->free = true;
    free_blocks.emplace(b->size, b);
  }

  void erase_free(Block* b) {
    auto range = free_blocks.equal_range(b->size);
    for (auto it = range.first; it != range.second; ++it) {
      if (it->second == b) {
        free_blocks.erase(it);
        return;
      }
    }
  }
};

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }
inline uint8_t* payload(Block* b) {
  return reinterpret_cast<uint8_t*>(b) + align_up(sizeof(Block));
}
inline Block* from_payload(void* p) {
  return reinterpret_cast<Block*>(static_cast<uint8_t*>(p) -
                                  align_up(sizeof(Block)));
}

}  // namespace

extern "C" {

void* pt_arena_create() { return new (std::nothrow) Arena(); }

void pt_arena_destroy(void* h) {
  // chunks are leaked intentionally on destroy-at-exit (OS reclaims); an
  // explicit chunk list isn't kept because blocks coalesce to chunk size.
  delete (Arena*)h;
}

void* pt_arena_alloc(void* h, size_t n) {
  auto* a = (Arena*)h;
  n = align_up(n ? n : kAlign);
  std::lock_guard<std::mutex> lk(a->mu);
  auto it = a->free_blocks.lower_bound(n);
  Block* b;
  if (it == a->free_blocks.end()) {
    // grow: one mmap chunk holding this request (and future ones)
    size_t hdr = align_up(sizeof(Block));
    size_t chunk = n + hdr > kMinChunk ? n + hdr : kMinChunk;
    void* mem = ::mmap(nullptr, chunk, PROT_READ | PROT_WRITE,
                       MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (mem == MAP_FAILED) return nullptr;
    a->total_reserved += chunk;
    b = (Block*)mem;
    b->size = chunk - hdr;
    b->prev = b->next = nullptr;
    b->free = false;
  } else {
    b = it->second;
    a->free_blocks.erase(it);
    b->free = false;
  }
  // split if the remainder is worth keeping
  size_t hdr = align_up(sizeof(Block));
  if (b->size >= n + hdr + kAlign) {
    Block* rest = (Block*)(payload(b) + n);
    rest->size = b->size - n - hdr;
    rest->prev = b;
    rest->next = b->next;
    if (rest->next) rest->next->prev = rest;
    b->next = rest;
    b->size = n;
    a->insert_free(rest);
  }
  a->total_in_use += b->size;
  if (a->total_in_use > a->peak_in_use) a->peak_in_use = a->total_in_use;
  a->alloc_count++;
  return payload(b);
}

void pt_arena_free(void* h, void* p) {
  if (!p) return;
  auto* a = (Arena*)h;
  Block* b = from_payload(p);
  std::lock_guard<std::mutex> lk(a->mu);
  a->total_in_use -= b->size;
  size_t hdr = align_up(sizeof(Block));
  // coalesce with next
  if (b->next && b->next->free) {
    Block* nx = b->next;
    a->erase_free(nx);
    b->size += hdr + nx->size;
    b->next = nx->next;
    if (b->next) b->next->prev = b;
  }
  // coalesce with prev
  if (b->prev && b->prev->free) {
    Block* pv = b->prev;
    a->erase_free(pv);
    pv->size += hdr + b->size;
    pv->next = b->next;
    if (pv->next) pv->next->prev = pv;
    b = pv;
  }
  a->insert_free(b);
}

void pt_arena_stats(void* h, uint64_t* reserved, uint64_t* in_use,
                    uint64_t* peak, uint64_t* allocs) {
  auto* a = (Arena*)h;
  std::lock_guard<std::mutex> lk(a->mu);
  *reserved = a->total_reserved;
  *in_use = a->total_in_use;
  *peak = a->peak_in_use;
  *allocs = a->alloc_count;
}

}  // extern "C"
