"""paddle_tpu.core — native (C++) runtime components.

The TPU compute path is JAX/XLA/Pallas; this package is the native runtime
*around* it (SURVEY.md §2.5): rendezvous store, shared-memory data
transport, host staging allocator, and the profiler's host tracer — the
pieces the reference implements in C++ (TCPStore, DataLoader workers,
AutoGrowthBestFitAllocator, HostTracer) and that stay native here.
"""
from .native import (  # noqa: F401
    available,
    load,
    load_error,
    HostArena,
    NativeTracer,
    ShmRing,
    TCPStore,
    TCPStoreServer,
)
