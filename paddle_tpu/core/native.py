"""ctypes bindings for the native runtime library (libpaddle_tpu_core.so).

The C++ sources live in paddle_tpu/core/cc/ and are compiled on first import
(g++ is part of the supported toolchain; no pybind11 — plain C ABI via
ctypes, per the environment constraints). Every consumer treats the native
layer as optional: ``available()`` gates it and pure-Python fallbacks exist
(e.g. the DataLoader falls back to multiprocessing queues).

Components bound here (reference analogs in each class docstring):
- TCPStore / TCPStoreServer  — rendezvous KV (tcp_store.h:121)
- ShmRing                    — DataLoader shared-memory batch transport
- HostArena                  — pooled host staging allocator
- NativeTracer               — low-overhead profiler span recorder
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libpaddle_tpu_core.so")
_SRC_DIR = os.path.join(_HERE, "cc")

_lib = None
_lib_err: Optional[str] = None
_build_lock = threading.Lock()


def _build() -> Optional[str]:
    srcs = [os.path.join(_SRC_DIR, f) for f in
            ("kv_store.cc", "shm_ring.cc", "host_arena.cc", "tracer.cc")]
    if not all(os.path.exists(s) for s in srcs):
        return "native sources missing"
    # rebuild when any source is newer than the .so
    if os.path.exists(_SO_PATH):
        so_mtime = os.path.getmtime(_SO_PATH)
        if all(os.path.getmtime(s) <= so_mtime for s in srcs):
            return None
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-pthread",
           "-shared", *srcs, "-lrt", "-o", _SO_PATH]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no g++ / hang
        return f"native build failed to run: {e}"
    if proc.returncode != 0:
        return f"native build failed:\n{proc.stderr[-2000:]}"
    return None


def _declare(lib):
    c = ctypes
    P, U8P = c.c_void_p, c.POINTER(c.c_uint8)
    sigs = {
        # kv store
        "pt_kv_server_start": ([c.c_int], P),
        "pt_kv_server_port": ([P], c.c_int),
        "pt_kv_server_stop": ([P], None),
        "pt_kv_connect": ([c.c_char_p, c.c_int, c.c_int], P),
        "pt_kv_disconnect": ([P], None),
        "pt_kv_set": ([P, c.c_char_p, U8P, c.c_uint32], c.c_int64),
        "pt_kv_get": ([P, c.c_char_p, c.c_int64, c.POINTER(U8P),
                       c.POINTER(c.c_uint32)], c.c_int64),
        "pt_kv_add": ([P, c.c_char_p, c.c_int64], c.c_int64),
        "pt_kv_check": ([P, c.c_char_p], c.c_int64),
        "pt_kv_delete": ([P, c.c_char_p], c.c_int64),
        "pt_kv_num_keys": ([P], c.c_int64),
        "pt_kv_compare_set": ([P, c.c_char_p, U8P, c.c_uint32, U8P,
                               c.c_uint32], c.c_int64),
        "pt_kv_free": ([U8P], None),
        # shm ring
        "pt_ring_open": ([c.c_char_p, c.c_uint64, c.c_uint32, c.c_int], P),
        "pt_ring_close": ([P], None),
        "pt_ring_slot_bytes": ([P], c.c_uint64),
        "pt_ring_n_slots": ([P], c.c_uint32),
        "pt_ring_acquire_write": ([P, c.POINTER(c.c_uint64), c.c_int], U8P),
        "pt_ring_commit_write": ([P, c.c_uint64, c.c_uint32, c.c_int64], None),
        "pt_ring_acquire_read": ([P, c.POINTER(c.c_uint32),
                                  c.POINTER(c.c_int64),
                                  c.POINTER(c.c_uint64), c.c_int], U8P),
        "pt_ring_release_read": ([P, c.c_uint64], None),
        "pt_ring_producer_done": ([P], None),
        "pt_ring_producers_done": ([P], c.c_uint32),
        "pt_ring_set_progress": ([P, c.c_uint64], None),
        "pt_ring_progress": ([P], c.c_uint64),
        "pt_ring_pending": ([P], c.c_uint64),
        # arena
        "pt_arena_create": ([], P),
        "pt_arena_destroy": ([P], None),
        "pt_arena_alloc": ([P, c.c_size_t], P),
        "pt_arena_free": ([P, P], None),
        "pt_arena_stats": ([P] + [c.POINTER(c.c_uint64)] * 4, None),
        # tracer
        "pt_trace_create": ([c.c_uint64], P),
        "pt_trace_destroy": ([P], None),
        "pt_trace_enable": ([P, c.c_int], None),
        "pt_trace_enabled": ([P], c.c_int),
        "pt_trace_intern": ([P, c.c_char_p], c.c_uint32),
        "pt_trace_now_ns": ([], c.c_uint64),
        "pt_trace_span": ([P, c.c_uint32, c.c_uint64, c.c_uint64], None),
        "pt_trace_end": ([P, c.c_uint32, c.c_uint64], None),
        "pt_trace_count": ([P], c.c_uint64),
        "pt_trace_dropped": ([P], c.c_uint64),
        "pt_trace_drain": ([P, c.POINTER(c.c_uint32), c.POINTER(c.c_uint32),
                            c.POINTER(c.c_uint64), c.POINTER(c.c_uint64),
                            c.c_uint64], c.c_uint64),
        "pt_trace_name": ([P, c.c_uint32, c.c_char_p, c.c_uint32], c.c_uint32),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype


def load():
    """Build (if needed) and load the native library. Returns the ctypes
    CDLL or None if unavailable (consumers must fall back)."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        err = _build()
        if err is not None:
            _lib_err = err
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            _declare(lib)
        except OSError as e:
            _lib_err = str(e)
            return None
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def load_error() -> Optional[str]:
    load()
    return _lib_err


# ---------------------------------------------------------------------------
# TCPStore
# ---------------------------------------------------------------------------

class TCPStoreServer:
    """Rank-0 daemon of the rendezvous store (MasterDaemon analog,
    /root/reference/paddle/phi/core/distributed/store/tcp_store.h)."""

    def __init__(self, port: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.pt_kv_server_start(port)
        if not self._h:
            raise RuntimeError(f"failed to start KV server on port {port}")

    @property
    def port(self) -> int:
        return self._lib.pt_kv_server_port(self._h)

    def stop(self):
        if self._h:
            self._lib.pt_kv_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client of the rendezvous store — paddle.distributed's Store API
    (set/get/add/wait/delete_key, tcp_store.h:121) over the native C++
    client. ``is_master=True`` also hosts the daemon in-process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, timeout: float = 900.0,
                 world_size: int = 1):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_lib_err}")
        self._lib = lib
        self._server = TCPStoreServer(port) if is_master else None
        if self._server is not None:
            port = self._server.port
        self.host, self.port = host, port
        self._timeout_ms = int(timeout * 1000)
        self._h = lib.pt_kv_connect(host.encode(), port, self._timeout_ms)
        if not self._h:
            raise RuntimeError(f"cannot connect to KV store {host}:{port}")
        self.world_size = world_size

    # Mirrors of the server's frame caps (kv_store.cc kMaxKeyLen/kMaxValLen):
    # checked client-side so a cooperative caller gets a deterministic error
    # without shipping a doomed multi-hundred-MiB payload first (the server
    # drain stays as the hostile-client backstop).
    MAX_KEY_LEN = 1 << 16
    MAX_VAL_LEN = 1 << 28

    def _check_frame(self, key: str, nval: int) -> None:
        if len(key.encode()) > self.MAX_KEY_LEN or nval > self.MAX_VAL_LEN:
            raise ValueError(
                f"KV frame for key {key!r} exceeds the store's size caps "
                f"(64KiB keys / 256MiB values)")

    def set(self, key: str, value) -> None:
        data = value.encode() if isinstance(value, str) else bytes(value)
        self._check_frame(key, len(data))
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
            else None
        rc = self._lib.pt_kv_set(self._h, key.encode(), buf, len(data))
        if rc == -(2 ** 63):
            raise RuntimeError("KV store connection lost")
        if rc == -3:
            raise ValueError(
                f"KV set({key!r}): frame exceeds the store's size caps "
                f"(64KiB keys / 256MiB values)")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint32()
        tmo = self._timeout_ms if timeout is None else int(timeout * 1000)
        rc = self._lib.pt_kv_get(self._h, key.encode(), tmo,
                                 ctypes.byref(out), ctypes.byref(out_len))
        if rc == -1:
            raise TimeoutError(f"KV get({key!r}) timed out after {tmo}ms")
        if rc == -(2 ** 63):
            raise RuntimeError("KV store connection lost")
        if not out or out_len.value == 0:
            return b""
        data = ctypes.string_at(out, out_len.value)
        self._lib.pt_kv_free(out)
        return data

    def add(self, key: str, amount: int = 1) -> int:
        rc = self._lib.pt_kv_add(self._h, key.encode(), amount)
        if rc == -(2 ** 63):
            raise RuntimeError("KV store connection lost")
        return int(rc)

    def check(self, key: str) -> bool:
        return self._lib.pt_kv_check(self._h, key.encode()) == 1

    def wait(self, key: str, timeout: Optional[float] = None) -> None:
        self.get(key, timeout=timeout)

    def delete_key(self, key: str) -> bool:
        return self._lib.pt_kv_delete(self._h, key.encode()) > 0

    def num_keys(self) -> int:
        return int(self._lib.pt_kv_num_keys(self._h))

    def compare_set(self, key: str, old: bytes, new: bytes) -> bool:
        self._check_frame(key, 4 + len(old) + len(new))
        ob = (ctypes.c_uint8 * len(old)).from_buffer_copy(old) if old else None
        nb = (ctypes.c_uint8 * len(new)).from_buffer_copy(new) if new else None
        rc = self._lib.pt_kv_compare_set(
            self._h, key.encode(), ob, len(old), nb, len(new))
        if rc == -(2 ** 63):  # a dead daemon must not read as CAS-miss:
            raise RuntimeError("KV store connection lost")  # retry loops spin
        if rc in (-3, -4):  # kStatusTooLarge / kStatusMalformed
            raise ValueError(
                f"KV compare_set({key!r}): frame rejected by the store "
                f"(status {rc})")
        return rc == 1

    def barrier(self, name: str = "barrier", world_size: Optional[int] = None,
                timeout: Optional[float] = None) -> None:
        """All ranks arrive, then all proceed (two-phase counter)."""
        n = world_size or self.world_size
        arrived = self.add(f"__bar/{name}/in", 1)
        if arrived == n:
            self.set(f"__bar/{name}/go", b"1")
        self.wait(f"__bar/{name}/go", timeout=timeout)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pt_kv_disconnect(self._h)
            self._h = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# ShmRing
# ---------------------------------------------------------------------------

class ShmRing:
    """Shared-memory batch ring (see cc/shm_ring.cc). Producer side writes
    serialized batches; consumer memoryviews them zero-copy."""

    def __init__(self, name: str, slot_bytes: int = 0, n_slots: int = 0,
                 create: bool = False):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native ring unavailable: {_lib_err}")
        self._lib = lib
        self.name = name
        self._h = lib.pt_ring_open(name.encode(), slot_bytes, n_slots,
                                   1 if create else 0)
        if not self._h:
            raise RuntimeError(f"shm ring open failed: {name}")
        self.slot_bytes = lib.pt_ring_slot_bytes(self._h)
        self.n_slots = lib.pt_ring_n_slots(self._h)

    def write(self, data: bytes, meta: int = 0, timeout_ms: int = 60000) -> bool:
        if len(data) > self.slot_bytes:
            raise ValueError(
                f"batch of {len(data)}B exceeds slot capacity "
                f"{self.slot_bytes}B; pass a larger shm_slot_bytes to "
                f"DataLoader")
        ticket = ctypes.c_uint64()
        ptr = self._lib.pt_ring_acquire_write(self._h, ctypes.byref(ticket),
                                              timeout_ms)
        if not ptr:
            return False
        ctypes.memmove(ptr, data, len(data))
        self._lib.pt_ring_commit_write(self._h, ticket.value, len(data), meta)
        return True

    def read(self, timeout_ms: int = 60000):
        """Returns (payload: bytes, meta: int) or None on timeout. The copy
        out of shared memory happens once here (np.frombuffer consumers use
        read_view instead)."""
        got = self.read_view(timeout_ms)
        if got is None:
            return None
        view, meta, ticket = got
        data = bytes(view)
        self.release(ticket)
        return data, meta

    def read_view(self, timeout_ms: int = 60000):
        """Zero-copy read: returns (memoryview, meta, ticket); caller MUST
        call release(ticket) when done with the view."""
        ln = ctypes.c_uint32()
        meta = ctypes.c_int64()
        ticket = ctypes.c_uint64()
        ptr = self._lib.pt_ring_acquire_read(
            self._h, ctypes.byref(ln), ctypes.byref(meta),
            ctypes.byref(ticket), timeout_ms)
        if not ptr:
            return None
        view = memoryview((ctypes.c_uint8 * ln.value).from_address(
            ctypes.addressof(ptr.contents))).cast("B")
        return view, meta.value, ticket.value

    def release(self, ticket: int):
        self._lib.pt_ring_release_read(self._h, ticket)

    def set_progress(self, v: int):
        self._lib.pt_ring_set_progress(self._h, v)

    def progress(self) -> int:
        return self._lib.pt_ring_progress(self._h)

    def producer_done(self):
        self._lib.pt_ring_producer_done(self._h)

    def producers_done(self) -> int:
        return self._lib.pt_ring_producers_done(self._h)

    def pending(self) -> int:
        return self._lib.pt_ring_pending(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.pt_ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# HostArena
# ---------------------------------------------------------------------------

class HostArena:
    """Pooled host staging allocator (cc/host_arena.cc). alloc() returns a
    numpy-wrappable address; see buffer()."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.pt_arena_create()

    def alloc(self, nbytes: int) -> int:
        p = self._lib.pt_arena_alloc(self._h, nbytes)
        if not p:
            raise MemoryError(f"host arena alloc of {nbytes}B failed")
        return p

    def free(self, addr: int):
        self._lib.pt_arena_free(self._h, ctypes.c_void_p(addr))

    def buffer(self, addr: int, nbytes: int) -> memoryview:
        return memoryview(
            (ctypes.c_uint8 * nbytes).from_address(addr)).cast("B")

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        self._lib.pt_arena_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {"reserved": vals[0].value, "in_use": vals[1].value,
                "peak": vals[2].value, "allocs": vals[3].value}

    def destroy(self):
        if getattr(self, "_h", None):
            self._lib.pt_arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# NativeTracer
# ---------------------------------------------------------------------------

class NativeTracer:
    """Span recorder (cc/tracer.cc) behind paddle_tpu.profiler.RecordEvent."""

    def __init__(self, capacity: int = 1 << 20):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native tracer unavailable: {_lib_err}")
        self._lib = lib
        self._h = lib.pt_trace_create(capacity)
        self._name_ids: dict = {}

    def enable(self, on: bool = True):
        self._lib.pt_trace_enable(self._h, 1 if on else 0)

    def intern(self, name: str) -> int:
        nid = self._name_ids.get(name)
        if nid is None:
            nid = self._lib.pt_trace_intern(self._h, name.encode())
            self._name_ids[name] = nid
        return nid

    def now_ns(self) -> int:
        return self._lib.pt_trace_now_ns()

    def span(self, name_id: int, t_start_ns: int, t_end_ns: int):
        self._lib.pt_trace_span(self._h, name_id, t_start_ns, t_end_ns)

    def end(self, name_id: int, t_start_ns: int):
        self._lib.pt_trace_end(self._h, name_id, t_start_ns)

    def drain(self):
        """Returns list of (name, tid, t_start_ns, t_end_ns)."""
        import numpy as np
        cap = int(self._lib.pt_trace_count(self._h))
        if cap == 0:
            return []
        ids = np.zeros(cap, np.uint32)
        tids = np.zeros(cap, np.uint32)
        starts = np.zeros(cap, np.uint64)
        ends = np.zeros(cap, np.uint64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        n = self._lib.pt_trace_drain(
            self._h, ids.ctypes.data_as(u32p), tids.ctypes.data_as(u32p),
            starts.ctypes.data_as(u64p), ends.ctypes.data_as(u64p), cap)
        out = []
        buf = ctypes.create_string_buffer(256)
        name_cache: dict = {}
        for k in range(int(n)):
            nid = int(ids[k])
            name = name_cache.get(nid)
            if name is None:
                self._lib.pt_trace_name(self._h, nid, buf, 256)
                name = buf.value.decode(errors="replace")
                name_cache[nid] = name
            out.append((name, int(tids[k]), int(starts[k]), int(ends[k])))
        return out

    def destroy(self):
        if getattr(self, "_h", None):
            self._lib.pt_trace_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
