"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability set, built on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `import paddle` (/root/reference/python/paddle/
__init__.py): tensor ops, nn, optimizer, amp, io, jit, distributed, vision.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (
    Tensor, Parameter, no_grad, enable_grad, is_grad_enabled, to_tensor,
    set_device, get_device, seed, get_rng_state, set_rng_state,
    get_default_dtype, set_default_dtype,
)
from .framework.dtype import (  # dtype aliases: paddle.float32 etc.
    bool_ as bool,  # noqa: A001
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, float8_e4m3fn, float8_e5m2,
)

from .tensor import *  # noqa: F401,F403 — op namespace at top level, like paddle
from . import tensor  # noqa: F401
from . import linalg  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import autograd  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import ops  # noqa: F401
from . import utils  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import static  # noqa: F401
from . import incubate  # noqa: F401
from . import sparse  # noqa: F401
from . import geometric  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import quantization  # noqa: F401
from . import inference  # noqa: F401
from . import device  # noqa: F401
from . import regularizer  # noqa: F401
from .hapi import callbacks  # noqa: F401  — paddle.callbacks alias
from .hapi import Model, summary, flops  # noqa: F401
from .framework.io import save, load  # noqa: F401

from .jit import to_static  # noqa: F401
from .autograd import grad  # noqa: F401

# paddle.DataParallel-style alias
from .distributed.parallel import DataParallel  # noqa: F401


def device_count() -> int:
    import jax
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def disable_static(place=None):
    from . import static as _static
    _static.disable_static()
    return None


def enable_static():
    from . import static as _static
    _static.enable_static()


def in_dynamic_mode() -> bool:
    from .static.program import in_static_mode
    return not in_static_mode()
