"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability set, built on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `import paddle` (/root/reference/python/paddle/
__init__.py): tensor ops, nn, optimizer, amp, io, jit, distributed, vision.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .utils import jax_compat as _jax_compat  # noqa: F401 — pre-import shims

from .framework import (
    Tensor, Parameter, no_grad, enable_grad, is_grad_enabled, to_tensor,
    set_device, get_device, seed, get_rng_state, set_rng_state,
    get_default_dtype, set_default_dtype,
)
from .framework.dtype import (  # dtype aliases: paddle.float32 etc.
    bool_ as bool,  # noqa: A001
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128, float8_e4m3fn, float8_e5m2,
)

from .tensor import *  # noqa: F401,F403 — op namespace at top level, like paddle
from . import tensor  # noqa: F401
# the star import above binds `linalg` to tensor.linalg (submodule name
# leak), and `from . import linalg` would short-circuit on that existing
# attribute — import the real namespace module explicitly
import importlib as _importlib
linalg = _importlib.import_module(".linalg", __name__)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import autograd  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import ops  # noqa: F401
from . import utils  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import hapi  # noqa: F401
from . import profiler  # noqa: F401
from . import static  # noqa: F401
from . import incubate  # noqa: F401
from . import sparse  # noqa: F401
from . import geometric  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import quantization  # noqa: F401
from . import inference  # noqa: F401
from . import decomposition  # noqa: F401
from . import cost_model  # noqa: F401
from . import onnx  # noqa: F401
from . import device  # noqa: F401
from . import regularizer  # noqa: F401
from .hapi import callbacks  # noqa: F401  — paddle.callbacks alias
from .hapi import Model, summary, flops  # noqa: F401
from .framework.io import save, load  # noqa: F401

from .jit import to_static  # noqa: F401
from .autograd import grad  # noqa: F401

# paddle.DataParallel-style alias
from .distributed.parallel import DataParallel  # noqa: F401


def device_count() -> int:
    import jax
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def disable_static(place=None):
    from . import static as _static
    _static.disable_static()
    return None


def enable_static():
    from . import static as _static
    _static.enable_static()


def in_dynamic_mode() -> bool:
    from .static.program import in_static_mode
    return not in_static_mode()

# --- top-level long tail (reference python/paddle/__init__.py) -------------


class CPUPlace:
    """Device place objects (reference CPUPlace/CUDAPlace/...); device
    selection on TPU goes through set_device — these exist so
    place-typed reference code constructs."""

    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace:
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(accelerator:{self.device_id})"


class CUDAPinnedPlace:
    def __repr__(self):
        return "Place(pinned)"


class LazyGuard:
    """Reference LazyGuard defers parameter initialization; paddle_tpu
    initializes eagerly (cheap on host, arrays are lazy on device
    anyway) — the guard is a transparent context."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


from .nn import ParamAttr  # noqa: F401,E402


def batch(reader, batch_size, drop_last=False):
    """Reference paddle.batch: wrap a sample reader into a batch
    reader."""
    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def check_shape(x):
    from .static.program import in_static_mode
    return list(x.shape)


def disable_signal_handler():
    """Reference disables paddle's C++ signal handlers; there are none
    here (pure-Python runtime) — accepted no-op by construction."""


dtype = _np_mod = None
from .framework import dtype as _dtype_mod  # noqa: E402


class dtype:  # noqa: F811 — paddle.dtype(type) constructor parity
    def __new__(cls, d):
        return _dtype_mod.convert_dtype(d)


def finfo(d):
    import numpy as _np
    return _np.finfo(_dtype_mod.convert_dtype(d))


def iinfo(d):
    import numpy as _np
    return _np.iinfo(_dtype_mod.convert_dtype(d))


def get_cuda_rng_state():
    """Accelerator RNG state (the reference's 'cuda' = the device)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)


def get_flags(flags):
    from .utils.flags import FLAGS
    if isinstance(flags, str):
        flags = [flags]
    return {f: getattr(FLAGS, f.replace("FLAGS_", ""), None)
            for f in flags}


def set_flags(flags):
    from .utils.flags import FLAGS
    for k, v in flags.items():
        setattr(FLAGS, k.replace("FLAGS_", ""), v)


def set_grad_enabled(mode: bool):
    from .framework.core import _grad_state

    class _Guard:
        def __init__(self):
            self._prev = _grad_state.enabled
            _grad_state.enabled = bool(mode)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            _grad_state.enabled = self._prev
            return False

    return _Guard()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def pdist(x, p=2.0, name=None):
    """Pairwise distances, condensed form (reference paddle.pdist)."""
    from . import tensor as _T
    import jax.numpy as _jnp
    from .framework.core import apply as _apply

    def f(a):
        nr = a.shape[0]
        d = _jnp.linalg.norm(a[:, None] - a[None, :] + 0.0, ord=p,
                             axis=-1)
        iu = _jnp.triu_indices(nr, k=1)
        return d[iu]
    return _apply("pdist", f, x)


def tolist(x):
    """Free-function form of Tensor.tolist (reference paddle.tolist) —
    does NOT re-register the method (that would shadow the original)."""
    import numpy as _np
    return _np.asarray(x._value if hasattr(x, "_value") else x).tolist()


# erf_/expm1_/square_ come from tensor._INPLACE_NAMES (star-exported)
