"""paddle_tpu.profiler — profiling with scheduled windows + Chrome export.

TPU-native re-imagination of the reference profiler
(/root/reference/python/paddle/profiler/profiler.py:346 Profiler,
:117 make_scheduler, :215 export_chrome_tracing): host spans are recorded
by the native C++ tracer (paddle_tpu/core/cc/tracer.cc — the HostTracer
analog, ~40ns/span instead of CUPTI); device-side tracing delegates to
``jax.profiler`` (xprof), the TPU equivalent of the reference's CudaTracer
(SURVEY.md §5.1). Both merge into one Chrome trace.

API parity:
    prof = Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.TPU],
                    scheduler=make_scheduler(closed=1, ready=1, record=3),
                    on_trace_ready=export_chrome_tracing('./log'))
    prof.start(); ...; prof.step(); ...; prof.stop()
    prof.summary()
plus RecordEvent spans and the throughput ``benchmark`` step timer
(timer.py analog).
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional

__all__ = [
    "Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "export_protobuf",
    "load_profiler_result", "SummaryView", "benchmark",
    "device_trace_summary",
]


def device_trace_summary(trace_dir: str) -> dict:
    """Summarize the DEVICE lanes of a jax.profiler (xprof) capture —
    the hardware proof that the §5.1 profiler row records real TPU
    kernel timelines, not just host spans (the reference's CudaTracer
    analog: /root/reference/paddle/fluid/platform/profiler/
    cuda_tracer.h). Parses the trace.json.gz the xprof plugin writes
    next to the .xplane.pb and returns {"device_lanes": [...],
    "device_events": N, "top_kernels": [...]} ({} lanes / 0 events on
    a host-only capture)."""
    import glob
    import gzip
    from collections import Counter

    out = {"device_lanes": [], "device_events": 0, "top_kernels": []}
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        return out
    tr = json.loads(gzip.open(paths[-1]).read())
    evs = tr.get("traceEvents", [])
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"
             and "name" in e.get("args", {})}
    dev_pids = {pid for pid, nm in procs.items()
                if "/device:" in nm and "CPU" not in nm}
    kernels = Counter()
    n = 0
    for e in evs:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            n += 1
            kernels[e.get("name", "?")] += 1
    out["device_lanes"] = sorted(procs[p] for p in dev_pids)
    out["device_events"] = n
    out["top_kernels"] = [k for k, _ in kernels.most_common(5)]
    return out


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1     # accepted for API compat; maps to the accelerator
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last record step of a window


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Window scheduler parity
    (/root/reference/python/paddle/profiler/profiler.py:117): step_num →
    state, cycling [closed, ready, record] after skip_first steps."""
    period = closed + ready + record
    if record <= 0:
        raise ValueError("record span must be positive")

    def fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return fn


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready callback writing a chrome://tracing JSON file."""
    seq = {"n": 0}

    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        # counter suffix: two windows can close within the same millisecond
        path = os.path.join(
            dir_name, f"{name}_time_{int(time.time() * 1000)}"
                      f"_{seq['n']}.paddle_trace.json")
        seq["n"] += 1
        prof._export_chrome(path)
        prof._last_export_path = path
    return handle


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """Reference exports a protobuf dump; here the same event list is
    serialized as JSON-lines (stable, dependency-free)."""
    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.paddle_trace.jsonl")
        with open(path, "w") as f:
            for ev in prof._events:
                f.write(json.dumps(ev) + "\n")
        prof._last_export_path = path
    return handle


def load_profiler_result(path: str) -> List[dict]:
    with open(path) as f:
        if path.endswith(".jsonl"):
            return [json.loads(l) for l in f if l.strip()]
        data = json.load(f)
        return data.get("traceEvents", data)


# ---------------------------------------------------------------------------
# RecordEvent
# ---------------------------------------------------------------------------

_active_profiler: Optional["Profiler"] = None


class RecordEvent:
    """User-instrumented span (parity: event_tracing RecordEvent). Usable
    as context manager or begin()/end(). Costs two clock reads + one
    lock-free native ring write when a profiler is recording; no-op
    otherwise."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._t0 = None
        self._prof = None

    def begin(self):
        prof = _active_profiler
        if prof is not None and prof._recording:
            self._prof = prof
            self._t0 = prof._tracer.now_ns() if prof._tracer else \
                time.perf_counter_ns()
        return self

    def end(self):
        prof = self._prof
        if prof is None or self._t0 is None:
            return
        if prof._tracer is not None:
            prof._tracer.end(prof._tracer.intern(self.name), self._t0)
        else:
            prof._py_events.append(
                (self.name, 0, self._t0, time.perf_counter_ns()))
        self._prof = None

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()
        return False


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------

class Profiler:
    def __init__(self, *, targets: Optional[list] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        if isinstance(scheduler, (tuple, list)):  # (start, end) batch range
            start, end = scheduler
            scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                       record=end - start, repeat=1)
        self.scheduler = scheduler or _default_scheduler
        self.on_trace_ready = on_trace_ready
        self.targets = targets or [ProfilerTarget.CPU]
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._recording = False
        self._events: List[dict] = []      # current window's chrome events
        self._delivered_events: List[dict] = []  # past windows (delivered)
        self._py_events: list = []         # fallback span store
        self._tracer = None
        self._device_trace_dir = None
        self._last_export_path = None
        self._step_info = _StepInfo()
        if not timer_only:
            try:
                from ..core.native import NativeTracer
                self._tracer = NativeTracer()
            except Exception:
                self._tracer = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        global _active_profiler
        _active_profiler = self
        self._step_info.reset()
        self.current_state = self.scheduler(self.step_num)
        self._apply_state(self.current_state)

    def stop(self):
        global _active_profiler
        if self._recording:
            self._recording = False  # before _drain: tracer must disable
            self._drain()
            self._stop_device_trace()
        if self.on_trace_ready is not None and self._events:
            self.on_trace_ready(self)
            self._delivered_events.extend(self._events)
            self._events = []  # delivered — don't re-export on next window
        _active_profiler = None

    def step(self, num_samples: Optional[int] = None):
        """Advance one training step; applies the scheduler transition."""
        self._step_info.step(num_samples)
        if self._recording:
            self._mark_step_boundary()
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        # RECORD_AND_RETURN marks the window's last step: deliver even if
        # the next window starts immediately (closed=0, ready=0)
        if prev == ProfilerState.RECORD_AND_RETURN or (
                prev == ProfilerState.RECORD
                and self.current_state not in (
                    ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)):
            # window closed → deliver trace
            self._recording = False  # before _drain: tracer must disable
            self._drain()
            self._stop_device_trace()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
                self._delivered_events.extend(self._events)
                self._events = []  # each window exports only its own spans
        self._apply_state(self.current_state)

    def _apply_state(self, st: ProfilerState):
        if st in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            if not self._recording:
                self._recording = True
                if self._tracer is not None:
                    self._tracer.enable(True)
                self._start_device_trace()

    @property
    def device_trace_dir(self):
        """Directory of the device (xprof) capture for the current or
        last recording window; None when no device target was traced.
        Feed it to device_trace_summary() for the TPU-lane proof."""
        return self._device_trace_dir

    # -- device (xprof) ----------------------------------------------------
    def _start_device_trace(self):
        if not any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU)
                   for t in self.targets):
            return
        try:
            import jax
            self._device_trace_dir = f"/tmp/paddle_tpu_xprof_{os.getpid()}_" \
                                     f"{self.step_num}"
            jax.profiler.start_trace(self._device_trace_dir)
        except Exception:
            self._device_trace_dir = None

    def _stop_device_trace(self):
        if self._device_trace_dir is None:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass

    # -- event collection --------------------------------------------------
    def _mark_step_boundary(self):
        now = (self._tracer.now_ns() if self._tracer
               else time.perf_counter_ns())
        self._events.append({
            "name": f"ProfileStep#{self.step_num}", "ph": "i",
            "ts": now / 1000.0, "pid": os.getpid(), "tid": 0,
            "s": "g", "cat": "Step",
        })

    def _drain(self):
        if self._tracer is not None:
            spans = self._tracer.drain()
            # keep recording if mid-window (export() can be called while
            # the scheduler is still in a RECORD state)
            self._tracer.enable(self._recording)
        else:
            spans, self._py_events = self._py_events, []
        for name, tid, t0, t1 in spans:
            self._events.append({
                "name": name, "ph": "X", "ts": t0 / 1000.0,
                "dur": (t1 - t0) / 1000.0, "pid": os.getpid(),
                "tid": tid, "cat": "Host",
            })

    def _export_chrome(self, path: str):
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms"}, f)

    def export(self, path: str, format: str = "json"):
        self._drain()
        self._export_chrome(path)

    @property
    def events(self) -> List[dict]:
        """All captured events — delivered windows + the current one."""
        return self._delivered_events + self._events

    # -- summaries ---------------------------------------------------------
    def summary(self, sorted_by=None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms") -> str:
        """Aggregated span table (profiler_statistic.py analog)."""
        stats: Dict[str, List[float]] = {}
        for ev in self.events:
            if ev.get("ph") != "X":
                continue
            stats.setdefault(ev["name"], []).append(ev["dur"] / 1000.0)
        unit = {"s": 1e-3, "ms": 1.0, "us": 1e3}.get(time_unit, 1.0)
        rows = []
        for name, durs in sorted(stats.items(),
                                 key=lambda kv: -sum(kv[1])):
            tot = sum(durs) * unit
            rows.append((name, len(durs), tot, tot / len(durs),
                         max(durs) * unit, min(durs) * unit))
        header = f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}" \
                 f"{'Avg':>12}{'Max':>12}{'Min':>12}"
        lines = [header, "-" * len(header)]
        for name, calls, tot, avg, mx, mn in rows:
            lines.append(f"{name[:39]:<40}{calls:>8}{tot:>14.3f}"
                         f"{avg:>12.3f}{mx:>12.3f}{mn:>12.3f}")
        lines.append("-" * len(header))
        lines.append(self._step_info.summary())
        return "\n".join(lines)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


# ---------------------------------------------------------------------------
# benchmark step timer — reference timer.py (ips logging used by
# hybrid-parallel training loops)
# ---------------------------------------------------------------------------

class _StepInfo:
    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()
        self._last = self._t0
        self._steps = 0
        self._samples = 0
        self._step_times: List[float] = []

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        self._step_times.append(now - self._last)
        self._last = now
        self._steps += 1
        if num_samples:
            self._samples += num_samples

    @property
    def ips(self) -> float:
        elapsed = self._last - self._t0
        if elapsed <= 0:
            return 0.0
        if self._samples:
            return self._samples / elapsed
        return self._steps / elapsed

    def summary(self) -> str:
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        st = np.asarray(self._step_times[1:] or self._step_times)
        what = "samples/s" if self._samples else "steps/s"
        return (f"steps: {self._steps}  avg step: {st.mean()*1000:.2f}ms  "
                f"p50: {np.percentile(st, 50)*1000:.2f}ms  "
                f"throughput: {self.ips:.2f} {what}")


class _Benchmark:
    """paddle.profiler.benchmark() parity — global step timer usable
    without a Profiler instance."""

    def __init__(self):
        self._info = _StepInfo()
        self._lock = threading.Lock()

    def begin(self):
        self._info.reset()

    def step(self, num_samples: Optional[int] = None):
        with self._lock:
            self._info.step(num_samples)

    def end(self):
        return self._info.summary()

    def speed_average(self) -> float:
        return self._info.ips

    def step_info(self, unit=None) -> str:
        return self._info.summary()


_benchmark = _Benchmark()


def benchmark() -> _Benchmark:
    return _benchmark
