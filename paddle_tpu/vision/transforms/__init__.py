"""paddle.vision.transforms parity (reference:
/root/reference/python/paddle/vision/transforms/__init__.py)."""
from .functional import (  # noqa: F401
    adjust_brightness, adjust_contrast, adjust_hue, adjust_saturation,
    affine, center_crop, crop, erase, hflip, normalize, pad, perspective,
    resize, rotate, to_grayscale, to_tensor, vflip,
)
from .transforms import (  # noqa: F401
    BaseTransform, BrightnessTransform, CenterCrop, ColorJitter, Compose,
    ContrastTransform, Grayscale, HueTransform, Normalize, Pad,
    RandomCrop, RandomErasing, RandomHorizontalFlip, RandomResizedCrop,
    RandomRotation, RandomVerticalFlip, Resize, SaturationTransform,
    ToTensor, Transpose,
    RandomAffine, RandomPerspective,
)
