"""Transform classes (parity:
/root/reference/python/paddle/vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "Transpose", "Normalize", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "RandomCrop",
    "Pad", "RandomRotation", "Grayscale", "RandomErasing",
    "RandomAffine", "RandomPerspective",
]


class BaseTransform:
    """Transform base; subclasses implement ``_apply_image``."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            out = []
            for key, data in zip(self.keys, inputs):
                if key == "image":
                    out.append(self._apply_image(data))
                else:
                    out.append(data)
            # elements beyond the declared keys (labels etc.) pass through
            out.extend(inputs[len(self.keys):])
            return tuple(out)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW', keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = F._to_np(img)
        if self.padding is not None:
            arr = F.pad(arr, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and h < th:
            arr = F.pad(arr, (0, th - h, 0, th - h), self.fill,
                        self.padding_mode)
            h = arr.shape[0]
        if self.pad_if_needed and w < tw:
            arr = F.pad(arr, (tw - w, 0, tw - w, 0), self.fill,
                        self.padding_mode)
            w = arr.shape[1]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(arr, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation='bilinear', keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = F._to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = F.crop(arr, top, left, ch, cw)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(arr, min(h, w)), self.size,
                        self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else \
            F._to_np(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else \
            F._to_np(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_np(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_np(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_np(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return F._to_np(img)
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [
            BrightnessTransform(brightness, keys),
            ContrastTransform(contrast, keys),
            SaturationTransform(saturation, keys),
            HueTransform(hue, keys),
        ]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation='nearest', expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = F._to_np(img)
        if random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / aspect)))
            ew = int(round(np.sqrt(target * aspect)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                return F.erase(arr, top, left, eh, ew, self.value,
                               self.inplace)
        return arr


class RandomAffine(BaseTransform):
    """Random affine (reference transforms.RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        from . import functional as F
        rng = np.random
        angle = rng.uniform(*self.degrees)
        arr = F._to_np(img)
        h, w = arr.shape[:2]
        tx = ty = 0.0
        if self.translate is not None:
            tx = rng.uniform(-self.translate[0], self.translate[0]) * w
            ty = rng.uniform(-self.translate[1], self.translate[1]) * h
        sc = rng.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif np.isscalar(self.shear):
            sh = (rng.uniform(-self.shear, self.shear), 0.0)
        else:
            lo, hi = self.shear[0], self.shear[1]
            sh = (rng.uniform(lo, hi), 0.0)
        return F.affine(img, angle, (tx, ty), sc, sh,
                        interpolation=self.interpolation, fill=self.fill,
                        center=self.center)


class RandomPerspective(BaseTransform):
    """Random perspective distortion (reference RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        from . import functional as F
        rng = np.random
        if rng.rand() > self.prob:
            return F._to_np(img)
        arr = F._to_np(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = int(w * d / 2), int(h * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(rng.randint(0, dx + 1), rng.randint(0, dy + 1)),
               (w - 1 - rng.randint(0, dx + 1), rng.randint(0, dy + 1)),
               (w - 1 - rng.randint(0, dx + 1),
                h - 1 - rng.randint(0, dy + 1)),
               (rng.randint(0, dx + 1), h - 1 - rng.randint(0, dy + 1))]
        return F.perspective(img, start, end,
                             interpolation=self.interpolation,
                             fill=self.fill)
