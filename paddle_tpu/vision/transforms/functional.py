"""Functional image transforms (parity:
/root/reference/python/paddle/vision/transforms/functional.py).

Host-side preprocessing: operates on numpy arrays (HWC, uint8 or float)
or PIL Images; returns numpy. Device work stays in the model — keeping
the input pipeline off the TPU is the TPU-native layout (feed bf16/f32
batches, let XLA own the chip).
"""
from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

from ...framework.core import Tensor

__all__ = [
    "to_tensor", "hflip", "vflip", "resize", "pad", "crop", "center_crop",
    "adjust_brightness", "adjust_contrast", "adjust_saturation",
    "adjust_hue", "normalize", "rotate", "to_grayscale", "erase",
]


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    try:
        from PIL import Image
        if isinstance(img, Image.Image):
            return np.asarray(img)
    except ImportError:
        pass
    return np.asarray(img)


def to_tensor(pic, data_format='CHW'):
    """uint8 HWC image → float32 tensor in [0,1], CHW by default."""
    arr = _to_np(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == 'CHW':
        arr = np.transpose(arr, (2, 0, 1))
    return Tensor(arr)


def hflip(img):
    return np.ascontiguousarray(_to_np(img)[:, ::-1])


def vflip(img):
    return np.ascontiguousarray(_to_np(img)[::-1])


# paddle/cv2 names → jax.image methods
_INTERP_METHODS = {
    "nearest": "nearest",
    "bilinear": "linear",
    "linear": "linear",
    "bicubic": "cubic",
    "cubic": "cubic",
    "lanczos3": "lanczos3",
    "lanczos5": "lanczos5",
}


def _interp_resize(arr, h, w, interpolation="bilinear"):
    """Resize via jax.image on host numpy (small images)."""
    import jax.image
    method = _INTERP_METHODS.get(interpolation)
    if method is None:
        raise ValueError(
            f"unsupported interpolation {interpolation!r}; one of "
            f"{sorted(_INTERP_METHODS)}")
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    src_dtype = arr.dtype
    out = jax.image.resize(arr.astype(np.float32),
                           (h, w, arr.shape[2]), method=method)
    out = np.asarray(out)
    if src_dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out[:, :, 0] if squeeze else out


def resize(img, size, interpolation='bilinear'):
    arr = _to_np(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h <= w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    return _interp_resize(arr, nh, nw, interpolation)


def pad(img, padding, fill=0, padding_mode='constant'):
    arr = _to_np(img)
    if isinstance(padding, numbers.Number):
        pl = pt = pr = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == 'constant':
        return np.pad(arr, pads, mode='constant', constant_values=fill)
    return np.pad(arr, pads, mode=padding_mode)


def crop(img, top, left, height, width):
    arr = _to_np(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(arr, top, left, th, tw)


def adjust_brightness(img, brightness_factor):
    arr = _to_np(img)
    dt = arr.dtype
    out = arr.astype(np.float32) * brightness_factor
    return np.clip(out, 0, 255 if dt == np.uint8 else 1.0).astype(dt)


def adjust_contrast(img, contrast_factor):
    arr = _to_np(img)
    dt = arr.dtype
    f = arr.astype(np.float32)
    gray = f.mean(axis=-1, keepdims=True).mean() if f.ndim == 3 else f.mean()
    out = gray + contrast_factor * (f - gray)
    return np.clip(out, 0, 255 if dt == np.uint8 else 1.0).astype(dt)


def adjust_saturation(img, saturation_factor):
    arr = _to_np(img)
    dt = arr.dtype
    f = arr.astype(np.float32)
    gray = f.mean(axis=-1, keepdims=True)
    out = gray + saturation_factor * (f - gray)
    return np.clip(out, 0, 255 if dt == np.uint8 else 1.0).astype(dt)


def adjust_hue(img, hue_factor):
    if not (-0.5 <= hue_factor <= 0.5):
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _to_np(img)
    dt = arr.dtype
    f = arr.astype(np.float32) / (255.0 if dt == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f[..., :3].max(-1)
    minc = f[..., :3].min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    rc = np.where(delta > 0, (maxc - r) / np.maximum(delta, 1e-12), 0.0)
    gc = np.where(delta > 0, (maxc - g) / np.maximum(delta, 1e-12), 0.0)
    bc = np.where(delta > 0, (maxc - b) / np.maximum(delta, 1e-12), 0.0)
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * fr)
    t = v * (1.0 - s * (1.0 - fr))
    i = i.astype(np.int32) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if dt == np.uint8:
        out = np.clip(np.round(out * 255.0), 0, 255).astype(np.uint8)
    else:
        out = out.astype(dt)
    return out


def normalize(img, mean, std, data_format='CHW', to_rgb=False):
    arr = _to_np(img).astype(np.float32)
    if to_rgb:  # input is BGR (cv2-style): flip the channel axis
        arr = arr[::-1] if data_format == 'CHW' else arr[..., ::-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == 'CHW':
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (arr - mean) / std


def rotate(img, angle, interpolation='nearest', expand=False, center=None,
           fill=0):
    """Rotate by angle (degrees, counter-clockwise) about the center.

    expand=True enlarges the canvas to hold the whole rotated image
    (only valid with center=None, like the reference).
    """
    arr = _to_np(img)
    h, w = arr.shape[:2]
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        oh = int(np.ceil(abs(h * cos) + abs(w * sin)))
        ow = int(np.ceil(abs(w * cos) + abs(h * sin)))
    else:
        oh, ow = h, w
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    ocy, ocx = ((oh - 1) / 2.0, (ow - 1) / 2.0) if center is None \
        else (center[1], center[0])
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing='ij')
    # inverse map: output pixel -> source pixel
    xs = cos * (xx - ocx) + sin * (yy - ocy) + cx
    ys = -sin * (xx - ocx) + cos * (yy - ocy) + cy
    if interpolation == 'bilinear':
        x0 = np.floor(xs).astype(np.int64)
        y0 = np.floor(ys).astype(np.int64)
        fx, fy = xs - x0, ys - y0
        acc = 0.0
        wsum = 0.0
        for dy, wy in ((0, 1 - fy), (1, fy)):
            for dx, wx in ((0, 1 - fx), (1, fx)):
                xi = np.clip(x0 + dx, 0, w - 1)
                yi = np.clip(y0 + dy, 0, h - 1)
                inside = ((x0 + dx >= 0) & (x0 + dx < w)
                          & (y0 + dy >= 0) & (y0 + dy < h))
                wgt = (wy * wx) * inside
                pix = arr[yi, xi].astype(np.float32)
                if arr.ndim == 3:
                    wgt = wgt[..., None]
                acc = acc + wgt * pix
                wsum = wsum + wgt
        valid = wsum > 1e-8
        out_f = np.where(valid, acc / np.maximum(wsum, 1e-8),
                         np.float32(fill))
        if arr.dtype == np.uint8:
            return np.clip(np.round(out_f), 0, 255).astype(np.uint8)
        return out_f.astype(arr.dtype)
    xi = np.round(xs).astype(np.int64)
    yi = np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out_shape = (oh, ow) + arr.shape[2:]
    out = np.full(out_shape, fill, dtype=arr.dtype)
    out[valid] = arr[yi[valid], xi[valid]]
    return out


def to_grayscale(img, num_output_channels=1):
    arr = _to_np(img)
    dt = arr.dtype
    f = arr.astype(np.float32)
    gray = (0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2])
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    if dt == np.uint8:
        gray = np.clip(np.round(gray), 0, 255).astype(np.uint8)
    return gray.astype(dt) if dt != np.uint8 else gray


def erase(img, i, j, h, w, v, inplace=False):
    arr = _to_np(img)
    # PIL/jax-backed arrays are read-only; inplace only works on a
    # writeable ndarray input
    out = arr if (inplace and arr.flags.writeable) else arr.copy()
    out[i:i + h, j:j + w] = v
    return out


def _inverse_warp(arr, xs, ys, interpolation, fill):
    """Sample arr at float source coords (xs, ys) [oh, ow] — shared by
    affine/perspective (same scheme as rotate)."""
    h, w = arr.shape[:2]
    if interpolation == "bilinear":
        x0 = np.floor(xs).astype(np.int64)
        y0 = np.floor(ys).astype(np.int64)
        fx, fy = xs - x0, ys - y0
        acc = 0.0
        wsum = 0.0
        for dy, wy in ((0, 1 - fy), (1, fy)):
            for dx, wx in ((0, 1 - fx), (1, fx)):
                xi = np.clip(x0 + dx, 0, w - 1)
                yi = np.clip(y0 + dy, 0, h - 1)
                inside = ((x0 + dx >= 0) & (x0 + dx < w)
                          & (y0 + dy >= 0) & (y0 + dy < h))
                wgt = (wy * wx) * inside
                pix = arr[yi, xi].astype(np.float32)
                if arr.ndim == 3:
                    wgt = wgt[..., None]
                acc = acc + wgt * pix
                wsum = wsum + wgt
        out = np.where(wsum > 1e-8, acc / np.maximum(wsum, 1e-8),
                       np.float32(fill))
        if arr.dtype == np.uint8:
            return np.clip(np.round(out), 0, 255).astype(np.uint8)
        return out.astype(arr.dtype)
    xi = np.round(xs).astype(np.int64)
    yi = np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full(xs.shape + arr.shape[2:], fill, arr.dtype)
    out[valid] = arr[yi[valid], xi[valid]]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine transform (reference transforms.functional.affine):
    rotation + translation + scale + shear about the center."""
    arr = _to_np(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in
              (shear if isinstance(shear, (list, tuple))
               else (shear, 0.0))]
    # forward matrix: T(center) R(angle) Shear Scale T(-center) + trans
    a = np.cos(rad - sy) / np.cos(sy)
    b = -np.cos(rad - sy) * np.tan(sx) / np.cos(sy) - np.sin(rad)
    c = np.sin(rad - sy) / np.cos(sy)
    d = -np.sin(rad - sy) * np.tan(sx) / np.cos(sy) + np.cos(rad)
    m = np.array([[a, b], [c, d]], np.float64) * scale
    inv = np.linalg.inv(m)
    tx, ty = translate
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ox = xx - cx - tx
    oy = yy - cy - ty
    xs = inv[0, 0] * ox + inv[0, 1] * oy + cx
    ys = inv[1, 0] * ox + inv[1, 1] * oy + cy
    return _inverse_warp(arr, xs, ys, interpolation, fill)


def _persp_coeffs(src, dst):
    """Solve the 8-dof homography mapping dst → src points."""
    A = []
    B = []
    for (xs, ys), (xd, yd) in zip(src, dst):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        B.append(xs)
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
        B.append(ys)
    return np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(B, np.float64))


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective transform (reference functional.perspective):
    startpoints (source corners) map to endpoints."""
    arr = _to_np(img)
    h, w = arr.shape[:2]
    co = _persp_coeffs(startpoints, endpoints)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = co[6] * xx + co[7] * yy + 1.0
    xs = (co[0] * xx + co[1] * yy + co[2]) / den
    ys = (co[3] * xx + co[4] * yy + co[5]) / den
    return _inverse_warp(arr, xs, ys, interpolation, fill)
