"""MNIST / FashionMNIST (parity:
/root/reference/python/paddle/vision/datasets/mnist.py).

Reads the standard idx-ubyte files (optionally gzipped). No network:
``image_path``/``label_path`` must point at local files (the zero-egress
TPU pods mount datasets read-only).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST"]


def _open(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_idx_images(path):
    with _open(path) as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad idx image magic {magic}")
        data = np.frombuffer(f.read(num * rows * cols), dtype=np.uint8)
    return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    with _open(path) as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad idx label magic {magic}")
        return np.frombuffer(f.read(num), dtype=np.uint8)


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if mode not in ("train", "test"):
            raise ValueError("mode must be 'train' or 'test'")
        self.mode = mode
        self.transform = transform
        self.backend = backend
        if image_path is None or label_path is None:
            root = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser(f"~/.cache/paddle_tpu/{self.NAME}"))
            stem = "train" if mode == "train" else "t10k"
            image_path = image_path or os.path.join(
                root, f"{stem}-images-idx3-ubyte.gz")
            label_path = label_path or os.path.join(
                root, f"{stem}-labels-idx1-ubyte.gz")
        if not os.path.exists(image_path):
            raise FileNotFoundError(
                f"{image_path} not found; place the idx files locally "
                "(no download in this environment)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
