"""DatasetFolder / ImageFolder (parity:
/root/reference/python/paddle/vision/datasets/folder.py)."""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder"]

IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.pgm',
                  '.tif', '.tiff', '.webp', '.npy')


def default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with open(path, "rb") as f:
        return np.asarray(Image.open(f).convert("RGB"))


def is_image_file(filename):
    return filename.lower().endswith(IMG_EXTENSIONS)


class DatasetFolder(Dataset):
    """root/class_x/xxx.png layout → (image, class_index) samples."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        valid = is_valid_file or (
            lambda p: p.lower().endswith(tuple(extensions)))
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, filenames in sorted(os.walk(cdir)):
                for fn in sorted(filenames):
                    path = os.path.join(dirpath, fn)
                    if valid(path):
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat folder of images → (image,) samples (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or default_loader
        self.transform = transform
        extensions = extensions or IMG_EXTENSIONS
        valid = is_valid_file or (
            lambda p: p.lower().endswith(tuple(extensions)))
        self.samples = []
        for dirpath, _, filenames in sorted(os.walk(root)):
            for fn in sorted(filenames):
                path = os.path.join(dirpath, fn)
                if valid(path):
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
