"""Cifar10/100 (parity:
/root/reference/python/paddle/vision/datasets/cifar.py).

Reads the python-pickle batch format from a local tar.gz (or extracted
directory). No network access.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100"]


class Cifar10(Dataset):
    _archive = "cifar-10-python.tar.gz"
    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if mode not in ("train", "test"):
            raise ValueError("mode must be 'train' or 'test'")
        self.mode = mode
        self.transform = transform
        if data_file is None:
            data_file = os.path.join(
                os.environ.get("PADDLE_TPU_DATA_HOME",
                               os.path.expanduser("~/.cache/paddle_tpu")),
                self._archive)
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{data_file} not found; place the archive locally "
                "(no download in this environment)")
        members = self._train_members if mode == "train" \
            else self._test_members
        datas, labels = [], []
        if os.path.isdir(data_file):
            for m in members:
                with open(os.path.join(data_file, m), "rb") as f:
                    batch = pickle.load(f, encoding="bytes")
                datas.append(batch[b"data"])
                labels.extend(batch[self._label_key])
        else:
            with tarfile.open(data_file, "r:*") as tar:
                for info in tar.getmembers():
                    base = os.path.basename(info.name)
                    if base in members:
                        batch = pickle.load(tar.extractfile(info),
                                            encoding="bytes")
                        datas.append(batch[b"data"])
                        labels.extend(batch[self._label_key])
        data = np.concatenate(datas, 0)
        self.images = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _archive = "cifar-100-python.tar.gz"
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"
