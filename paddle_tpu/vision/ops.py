"""paddle.vision.ops — detection operators.

Reference: /root/reference/python/paddle/vision/ops.py (nms, roi_align,
roi_pool, box_coder, distribute_fpn_proposals, deform_conv2d, yolo_*)
backed by CUDA kernels. TPU-native: every op is a fixed-shape jnp/lax
composition — NMS is an O(N^2) IoU matrix + lax.fori suppression sweep
(the MXU eats the matrix; no dynamic shapes), RoI align is vectorized
bilinear gather. All differentiable where the reference's are.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply, apply_nodiff
from ..nn.layer.layers import Layer as _Layer

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "PSRoIPool", "RoIAlign", "RoIPool"]


def _iou_matrix(boxes):
    """[N, 4] xyxy → [N, N] IoU."""
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] of two xyxy box sets."""
    def f(a, b):
        x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
        y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
        x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
        y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
        inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
            jnp.maximum(a[:, 3] - a[:, 1], 0)
        area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
            jnp.maximum(b[:, 3] - b[:, 1], 0)
        return inter / jnp.maximum(
            area_a[:, None] + area_b[None, :] - inter, 1e-10)
    return apply("box_iou", f, boxes1, boxes2)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None):
    """paddle.vision.ops.nms parity: returns kept indices sorted by
    score. Class-aware when category_idxs given (boxes of different
    classes never suppress each other). Fixed-shape XLA impl: sort by
    score, O(N^2) IoU, sequential suppression via lax.fori_loop."""
    def f(bx, *rest):
        it = iter(rest)
        sc = next(it) if scores is not None else jnp.arange(
            bx.shape[0], 0.0, -1.0)
        cats = next(it) if category_idxs is not None else None
        n = bx.shape[0]
        order = jnp.argsort(-sc)
        b = bx[order]
        iou = _iou_matrix(b)
        if cats is not None:
            c = cats[order]
            same = c[:, None] == c[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            # suppress i if any kept earlier box overlaps it too much
            overlap = (iou[i] > iou_threshold) & keep & \
                (jnp.arange(n) < i)
            return keep.at[i].set(~overlap.any())

        keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
        kept_sorted = jnp.where(keep, jnp.arange(n), n)
        sel = jnp.sort(kept_sorted)  # keep score order, pad with n
        idx = order[jnp.minimum(sel, n - 1)]
        valid = sel < n
        count = valid.sum()
        # compact to the front, invalid slots filled with -1
        idx = jnp.where(valid, idx, -1)
        return idx, count

    args = (boxes,) + ((scores,) if scores is not None else ()) + \
        ((category_idxs,) if category_idxs is not None else ())
    idx, count = apply_nodiff("nms", f, *args)
    # host-side compaction to the reference's variable-length result
    arr = np.asarray(idx._value)
    arr = arr[arr >= 0]
    if top_k is not None:
        arr = arr[:top_k]
    return Tensor(jnp.asarray(arr, jnp.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True,
              name=None):
    """paddle.vision.ops.roi_align parity: x [N,C,H,W], boxes [R,4] xyxy
    in input coords, boxes_num [N] rois per image. Bilinear-sampled
    [R, C, oh, ow]; differentiable w.r.t. x.

    sampling_ratio=-1 (adaptive): the reference derives the grid per bin
    as ceil(roi_size/output_size). A data-dependent grid is not a static
    XLA shape, so eager calls size one shared grid for the largest RoI
    (capped at 8x8); under jit tracing this falls back to a fixed 2x2
    grid — a small numeric deviation from the reference for very large
    RoIs. Pass an explicit sampling_ratio for bit-stable behavior."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    if sampling_ratio > 0:
        samp = sampling_ratio
    else:
        # Adaptive: one shared grid sized for the largest RoI (a denser
        # uniform grid over-samples small bins, converging to the same bin
        # integral). Resolved from the user-facing boxes BEFORE any
        # autograd/jit tracing so training and eval agree; falls back to
        # 2x2 under to_static tracing or with zero RoIs.
        # NOTE: reading boxes forces a device→host sync; on eager hot
        # paths pass an explicit sampling_ratio to avoid it.
        samp = 2
        try:
            b = np.asarray(getattr(boxes, "value", boxes), dtype=np.float64)
            if b.shape[0]:
                brw = np.maximum((b[:, 2] - b[:, 0]) * spatial_scale,
                                 1e-3 if aligned else 1.0)
                brh = np.maximum((b[:, 3] - b[:, 1]) * spatial_scale,
                                 1e-3 if aligned else 1.0)
                peak = max(brh.max() / oh, brw.max() / ow)
                if np.isfinite(peak):  # NaN/Inf boxes: keep the 2x2 grid
                    samp = max(1, min(int(np.ceil(peak)), 8))
        except jax.errors.ConcretizationTypeError:
            pass

    if boxes.shape[0] == 0:  # static shape: no RoIs → empty result
        return apply("roi_align",
                     lambda xa, bxs, bn: jnp.zeros(
                         (0, xa.shape[1], oh, ow), xa.dtype),
                     x, boxes, boxes_num)

    def f(xa, bxs, bn):
        n, c, h, w = xa.shape
        r = bxs.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), bn, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        s = samp
        # sample grid: [r, oh, ow, s, s]
        iy = (jnp.arange(s) + 0.5) / s
        ix = (jnp.arange(s) + 0.5) / s
        gy = (y1[:, None, None] + (jnp.arange(oh)[None, :, None]
                                   + iy[None, None, :]) *
              bin_h[:, None, None])           # [r, oh, s]
        gx = (x1[:, None, None] + (jnp.arange(ow)[None, :, None]
                                   + ix[None, None, :]) *
              bin_w[:, None, None])           # [r, ow, s]

        def bilinear(img, yy, xx):
            """img [c,h,w]; yy/xx [...]."""
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy, 0, h - 1) - y0
            wx = jnp.clip(xx, 0, w - 1) - x0
            y0 = y0.astype(jnp.int32)
            x0 = x0.astype(jnp.int32)
            y1i = y1i.astype(jnp.int32)
            x1i = x1i.astype(jnp.int32)
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1i]
            v10 = img[:, y1i, x0]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        def per_roi(ri):
            img = xa[img_idx[ri]]
            yy = gy[ri][:, None, :, None]      # [oh,1,s,1]
            xx = gx[ri][None, :, None, :]      # [1,ow,1,s]
            yy = jnp.broadcast_to(yy, (oh, ow, s, s))
            xx = jnp.broadcast_to(xx, (oh, ow, s, s))
            vals = bilinear(img, yy, xx)       # [c, oh, ow, s, s]
            return vals.mean(axis=(-1, -2))    # [c, oh, ow]

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply("roi_align", f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None, _reduce: str = "max"):
    """Max-pool RoI extraction (reference roi_pool): [R, C, oh, ow].
    _reduce='mean' gives the average-pool variant PSRoIPool needs."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def f(xa, bxs, bn):
        n, c, h, w = xa.shape
        r = bxs.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), bn, total_repeat_length=r)
        x1 = jnp.floor(bxs[:, 0] * spatial_scale)
        y1 = jnp.floor(bxs[:, 1] * spatial_scale)
        x2 = jnp.ceil(bxs[:, 2] * spatial_scale)
        y2 = jnp.ceil(bxs[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)

        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def per_roi(ri):
            img = xa[img_idx[ri]]
            # bin id of every pixel (or -1 outside the roi)
            by = jnp.floor((ys - y1[ri]) / rh[ri] * oh)
            bxp = jnp.floor((xs - x1[ri]) / rw[ri] * ow)
            by = jnp.where((ys >= y1[ri]) & (ys < y1[ri] + rh[ri]),
                           jnp.clip(by, 0, oh - 1), -1)
            bxp = jnp.where((xs >= x1[ri]) & (xs < x1[ri] + rw[ri]),
                            jnp.clip(bxp, 0, ow - 1), -1)
            mask = (by[:, None, None, None] ==
                    jnp.arange(oh)[None, None, :, None]) & \
                   (bxp[None, :, None, None] ==
                    jnp.arange(ow)[None, None, None, :])  # [h,w,oh,ow]
            if _reduce == "mean":
                s = jnp.where(mask[None], img[:, :, :, None, None],
                              0.0).sum(axis=(1, 2))
                cnt = mask.sum(axis=(0, 1))
                return s / jnp.maximum(cnt, 1)[None]
            vals = jnp.where(mask[None], img[:, :, :, None, None],
                             -jnp.inf)
            out = vals.max(axis=(1, 2))        # [c, oh, ow]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply("roi_pool", f, x, boxes, boxes_num)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """SSD-style box encode/decode (reference box_coder). Decode
    supports [N, M, 4] target boxes with priors broadcast along `axis`
    (0: priors along N, 1: priors along M); prior_box_var may be None
    (treated as ones), a 4-vector, or per-box [N, 4]."""
    var_is_none = prior_box_var is None

    def f(pb, tb, *rest):
        pbv = rest[0] if rest else jnp.ones_like(pb)
        if pbv.ndim == 1:
            pbv = jnp.broadcast_to(pbv, pb.shape)
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx - pcx) / pw / pbv[:, 0]
            dy = (tcy - pcy) / ph / pbv[:, 1]
            dw = jnp.log(tw / pw) / pbv[:, 2]
            dh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([dx, dy, dw, dh], axis=1)
        # decode_center_size: broadcast priors across [N, M, 4] targets
        if tb.ndim == 3:
            exp = (slice(None), None) if axis == 0 else (None, slice(None))
            pw, ph, pcx, pcy = (v[exp] for v in (pw, ph, pcx, pcy))
            pbv = pbv[exp + (slice(None),)]
            v0, v1, v2, v3 = (pbv[..., k] for k in range(4))
        else:
            v0, v1, v2, v3 = (pbv[:, k] for k in range(4))
        dcx = v0 * tb[..., 0] * pw + pcx
        dcy = v1 * tb[..., 1] * ph + pcy
        dw = jnp.exp(v2 * tb[..., 2]) * pw
        dh = jnp.exp(v3 * tb[..., 3]) * ph
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm,
                          dcy + dh * 0.5 - norm], axis=-1)

    args = (prior_box, target_box) + \
        (() if var_is_none else (prior_box_var,))
    return apply("box_coder", f, *args)


class RoIAlign:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    """Position-sensitive RoI AVERAGE pooling: input channels = C*oh*ow;
    each output bin averages its own channel group (reference
    psroi_pool, vision/ops.py — 'position-sensitive average pooling')."""

    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size if isinstance(
            output_size, (tuple, list)) else (output_size, output_size)
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        oh, ow = self.output_size
        pooled = roi_pool(x, boxes, boxes_num, (oh, ow),
                          self.spatial_scale, _reduce="mean")

        def f(p):
            r, c_all, _, _ = p.shape
            c = c_all // (oh * ow)
            p = p.reshape(r, c, oh, ow, oh, ow)
            # bin (i,j) takes channel-group (i,j)
            i = jnp.arange(oh)[:, None]
            j = jnp.arange(ow)[None, :]
            return p[:, :, i, j, i, j]
        return apply("psroi_select", f, pooled)


# ---------------------------------------------------------------------------
# detection long tail (reference vision/ops.py): real implementations —
# anchor generation, YOLO box decoding, matrix NMS, PSRoI pooling,
# deformable conv (bilinear-gather formulation), FPN routing, proposal
# generation, jpeg IO. yolo_loss remains a loud stub (its target-
# assignment spec is large; COVERAGE.md notes the gap).
# ---------------------------------------------------------------------------

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (reference vision/ops.py prior_box)."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = []
    for ar in aspect_ratios:
        ars.append(ar)
        if flip and ar != 1.0:
            ars.append(1.0 / ar)
    boxes = []
    for s in min_sizes:
        for ar in ars:
            boxes.append((s * np.sqrt(ar), s / np.sqrt(ar)))
        if max_sizes:
            for smax in max_sizes:
                sp = np.sqrt(s * smax)
                boxes.append((sp, sp))
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    out = np.zeros((fh, fw, len(boxes), 4), np.float32)
    for k, (bw, bh) in enumerate(boxes):
        out[..., k, 0] = (cxg - bw / 2) / iw
        out[..., k, 1] = (cyg - bh / 2) / ih
        out[..., k, 2] = (cxg + bw / 2) / iw
        out[..., k, 3] = (cyg + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None,
             scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference yolo_box)."""
    def f(xa, imgs):
        b, c, h, w = xa.shape
        na = len(anchors) // 2
        an = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
        xa = xa.reshape(b, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (sig(xa[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx) / w
        by = (sig(xa[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy) / h
        in_w = w * downsample_ratio
        in_h = h * downsample_ratio
        bw = jnp.exp(xa[:, :, 2]) * an[None, :, 0, None, None] / in_w
        bh = jnp.exp(xa[:, :, 3]) * an[None, :, 1, None, None] / in_h
        conf = sig(xa[:, :, 4])
        probs = sig(xa[:, :, 5:]) * conf[:, :, None]
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * iw
        y1 = (by - bh / 2) * ih
        x2 = (bx + bw / 2) * iw
        y2 = (by + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) \
            .transpose(0, 1, 3, 4, 2).reshape(b, -1, 4)
        mask = (conf > conf_thresh).astype(boxes.dtype)
        boxes = boxes * mask.reshape(b, -1)[..., None]
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(b, -1, class_num)
        return boxes, scores
    return apply_nodiff("yolo_box", f, x, img_size)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    raise NotImplementedError(
        "yolo_loss: the YOLOv3 target-assignment spec is not "
        "implemented (COVERAGE.md gap); compose yolo_box with your own "
        "assignment, or use generic detection losses")


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms, SOLOv2): score decay from the
    IoU matrix instead of hard suppression. Host-side (detection post-
    processing)."""
    bb = np.asarray(bboxes._value if isinstance(bboxes, Tensor)
                    else bboxes)
    sc = np.asarray(scores._value if isinstance(scores, Tensor)
                    else scores)
    outs, indices, nums = [], [], []
    b, c, n = sc.shape
    for bi in range(b):
        dets = []
        idxs = []
        for ci in range(c):
            if ci == background_label:
                continue
            s = sc[bi, ci]
            keep = np.where(s > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            boxes_c = bb[bi, order]
            s_c = s[order]
            x1, y1, x2, y2 = boxes_c.T
            off = 0.0 if normalized else 1.0
            area = np.maximum(x2 - x1 + off, 0) * \
                np.maximum(y2 - y1 + off, 0)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            inter = np.maximum(ix2 - ix1 + off, 0) * \
                np.maximum(iy2 - iy1 + off, 0)
            iou = inter / np.maximum(area[:, None] + area[None, :]
                                     - inter, 1e-9)
            iou = np.triu(iou, 1)
            iou_cmax = iou.max(axis=0)
            if use_gaussian:
                decay = np.exp((iou_cmax ** 2 - iou ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / np.maximum(1 - iou_cmax[:, None],
                                                1e-9)).min(axis=0)
            s_dec = s_c * decay
            ok = s_dec > post_threshold
            for j in np.where(ok)[0]:
                dets.append([ci, s_dec[j], *boxes_c[j]])
                idxs.append(order[j])
        dets = np.asarray(dets, np.float32) if dets else \
            np.zeros((0, 6), np.float32)
        if dets.shape[0] > keep_top_k >= 0:
            top = np.argsort(-dets[:, 1])[:keep_top_k]
            dets = dets[top]
            idxs = [idxs[i] for i in top]
        outs.append(dets)
        indices.extend(idxs)
        nums.append(dets.shape[0])
    out = Tensor(jnp.asarray(np.concatenate(outs, 0)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(indices, np.int32))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(res) if len(res) > 1 else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool): channel
    group (i, j) pools from spatial bin (i, j)."""
    def f(xa, bx):
        b, c, h, w = xa.shape
        oh = ow = output_size if isinstance(output_size, int) \
            else output_size[0]
        oc = c // (oh * ow)
        outs = []
        for r in range(bx.shape[0]):
            x1, y1, x2, y2 = bx[r] * spatial_scale
            rh = jnp.maximum(y2 - y1, 1e-4) / oh
            rw = jnp.maximum(x2 - x1, 1e-4) / ow
            pooled = jnp.zeros((oc, oh, ow), xa.dtype)
            for i in range(oh):
                for j in range(ow):
                    # average over the bin via a soft mask (static shape)
                    ys = jnp.arange(h, dtype=jnp.float32)
                    xs = jnp.arange(w, dtype=jnp.float32)
                    my = ((ys >= y1 + i * rh) &
                          (ys < y1 + (i + 1) * rh)).astype(xa.dtype)
                    mx = ((xs >= x1 + j * rw) &
                          (xs < x1 + (j + 1) * rw)).astype(xa.dtype)
                    mask = my[:, None] * mx[None, :]
                    grp = xa[0, (i * ow + j) * oc:(i * ow + j + 1) * oc]
                    s = (grp * mask[None]).sum(axis=(1, 2))
                    cnt = jnp.maximum(mask.sum(), 1.0)
                    pooled = pooled.at[:, i, j].set(s / cnt)
            outs.append(pooled)
        return jnp.stack(outs)
    return apply("psroi_pool", f, x, boxes)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference deform_conv2d) as a
    bilinear-gather + matmul: offsets bend each kernel tap's sampling
    point; v2 modulation via `mask`. MXU-friendly (one big matmul over
    gathered patches)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)

    def f(xa, off, w, *rest):
        b, cin, h, wdt = xa.shape
        cout, cin_g, kh, kw = w.shape
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (wdt + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        xa_p = jnp.pad(xa, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        # base sampling grid per output position and tap
        oy = jnp.arange(oh) * sh
        ox = jnp.arange(ow) * sw
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        # offsets: [b, 2*dg*kh*kw, oh, ow] (y then x per tap)
        off = off.reshape(b, deformable_groups, 2, kh * kw, oh, ow)
        oy_ = off[:, :, 0].reshape(b, deformable_groups, kh, kw, oh, ow)
        ox_ = off[:, :, 1].reshape(b, deformable_groups, kh, kw, oh, ow)
        # sampling positions [b, dg, oh, ow, kh, kw]
        yy = base_y[None, None] + oy_.transpose(0, 1, 4, 5, 2, 3)
        xx = base_x[None, None] + ox_.transpose(0, 1, 4, 5, 2, 3)
        hp, wp = xa_p.shape[2], xa_p.shape[3]
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0

        def gather(yi, xi):
            yi_c = jnp.clip(yi.astype(jnp.int32), 0, hp - 1)
            xi_c = jnp.clip(xi.astype(jnp.int32), 0, wp - 1)
            valid = ((yi >= 0) & (yi <= hp - 1) &
                     (xi >= 0) & (xi <= wp - 1)).astype(xa.dtype)
            # per deformable group, gather its channel slab
            cg = cin // deformable_groups
            slabs = []
            for g in range(deformable_groups):
                slab = xa_p[:, g * cg:(g + 1) * cg]    # [b, cg, hp, wp]
                bi = jnp.arange(b)[:, None, None, None, None]
                gat = slab[bi, :, yi_c[:, g], xi_c[:, g]]
                # gat: [b, oh, ow, kh, kw, cg] → [b, cg, oh, ow, kh, kw]
                slabs.append(jnp.moveaxis(gat, -1, 1)
                             * valid[:, g][:, None])
            return jnp.concatenate(slabs, axis=1)

        v = (gather(y0, x0) * ((1 - wy) * (1 - wx)).repeat(
                cin // deformable_groups, axis=1).reshape(
                b, cin, oh, ow, kh, kw)
             + gather(y0, x0 + 1) * ((1 - wy) * wx).repeat(
                cin // deformable_groups, axis=1).reshape(
                b, cin, oh, ow, kh, kw)
             + gather(y0 + 1, x0) * (wy * (1 - wx)).repeat(
                cin // deformable_groups, axis=1).reshape(
                b, cin, oh, ow, kh, kw)
             + gather(y0 + 1, x0 + 1) * (wy * wx).repeat(
                cin // deformable_groups, axis=1).reshape(
                b, cin, oh, ow, kh, kw))
        rest_i = 0
        mod = None
        if mask is not None:
            mod = rest[rest_i]
            rest_i += 1
            mod = mod.reshape(b, deformable_groups, kh, kw, oh, ow) \
                .transpose(0, 1, 4, 5, 2, 3)
            v = v * mod.repeat(cin // deformable_groups, axis=1) \
                .reshape(b, cin, oh, ow, kh, kw)
        # contraction: out[b,co,oh,ow] = sum_ci,kh,kw v * w
        out = jnp.einsum("bcoykl,dckl->bdoy",
                         v.reshape(b, cin, oh, ow, kh, kw), w)
        if bias is not None:
            bval = rest[rest_i]
            out = out + bval[None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply("deform_conv2d", f, *args)


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d (reference vision/ops.py
    DeformConv2D). A real nn.Layer: weight/bias register in
    parameters()/state_dict and train under any optimizer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn.initializer import XavierUniform
        from ..framework.core import Parameter
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else kernel_size
        init = XavierUniform()
        self.weight = Parameter(init(
            (out_channels, in_channels // groups, kh, kw), "float32"))
        self.bias = None
        if bias_attr is not False:
            self.bias = Parameter(jnp.zeros((out_channels,), jnp.float32))
        self._cfg = dict(stride=stride, padding=padding,
                         dilation=dilation,
                         deformable_groups=deformable_groups,
                         groups=groups)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (reference
    distribute_fpn_proposals). Host-side."""
    rois = np.asarray(fpn_rois._value if isinstance(fpn_rois, Tensor)
                      else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
                    * np.maximum(rois[:, 3] - rois[:, 1] + off, 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, idxs = [], []
    for level in range(min_level, max_level + 1):
        sel = np.where(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros(0, int)
    restore = np.argsort(order).astype(np.int32).reshape(-1, 1)
    nums = [Tensor(jnp.asarray(np.asarray([len(i)], np.int32)))
            for i in idxs]
    if rois_num is not None:
        return outs, Tensor(jnp.asarray(restore)), nums
    return outs, Tensor(jnp.asarray(restore))


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference generate_proposals):
    decode → clip → filter → NMS, host-side per image."""
    sc = np.asarray(scores._value if isinstance(scores, Tensor)
                    else scores)
    bd = np.asarray(bbox_deltas._value
                    if isinstance(bbox_deltas, Tensor) else bbox_deltas)
    ims = np.asarray(img_size._value if isinstance(img_size, Tensor)
                     else img_size)
    an = np.asarray(anchors._value if isinstance(anchors, Tensor)
                    else anchors).reshape(-1, 4)
    va = np.asarray(variances._value if isinstance(variances, Tensor)
                    else variances).reshape(-1, 4)
    b = sc.shape[0]
    all_rois, all_probs, nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for bi in range(b):
        n_before = len(all_rois)
        s = sc[bi].transpose(1, 2, 0).reshape(-1)
        d = bd[bi].transpose(1, 2, 0).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = va[:, 0] * d[:, 0] * aw + acx
        cy = va[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(va[:, 2] * d[:, 2], 10)) * aw
        h = np.exp(np.minimum(va[:, 3] * d[:, 3], 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=1)
        ih, iw = ims[bi][0], ims[bi][1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keep = np.where((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                        & (boxes[:, 3] - boxes[:, 1] + off >= min_size))[0]
        s, boxes = s[keep], boxes[keep]
        order = np.argsort(-s)[:pre_nms_top_n]
        s, boxes = s[order], boxes[order]
        pick = []
        while order.size and len(pick) < post_nms_top_n:
            i = 0
            pick.append(i)
            x1 = np.maximum(boxes[i, 0], boxes[:, 0])
            y1 = np.maximum(boxes[i, 1], boxes[:, 1])
            x2 = np.minimum(boxes[i, 2], boxes[:, 2])
            y2 = np.minimum(boxes[i, 3], boxes[:, 3])
            inter = np.maximum(x2 - x1 + off, 0) * \
                np.maximum(y2 - y1 + off, 0)
            a_i = (boxes[:, 2] - boxes[:, 0] + off) * \
                (boxes[:, 3] - boxes[:, 1] + off)
            iou = inter / np.maximum(a_i[i] + a_i - inter, 1e-9)
            rest = np.where(iou <= nms_thresh)[0]
            rest = rest[rest != i]
            sel = boxes[i:i + 1]
            all_rois.append(sel)
            all_probs.append(s[i:i + 1])
            boxes, s, order = boxes[rest], s[rest], order[rest]
        nums.append(len(all_rois) - n_before)
    rois = np.concatenate(all_rois, 0) if all_rois \
        else np.zeros((0, 4), np.float32)
    probs = np.concatenate(all_probs, 0) if all_probs \
        else np.zeros((0,), np.float32)
    out = (Tensor(jnp.asarray(rois.astype(np.float32))),
           Tensor(jnp.asarray(probs.astype(np.float32)[:, None])))
    if return_rois_num:
        out = out + (Tensor(jnp.asarray(np.asarray(nums, np.int32))),)
    return out


def read_file(filename, name=None):
    """Read raw bytes as a uint8 tensor (reference read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference decode_jpeg;
    PIL plays the role of the reference's nvjpeg)."""
    import io as _io
    from PIL import Image
    data = np.asarray(x._value if isinstance(x, Tensor) else x,
                      np.uint8).tobytes()
    img = Image.open(_io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["prior_box", "yolo_box", "yolo_loss", "matrix_nms",
            "psroi_pool", "deform_conv2d", "DeformConv2D",
            "distribute_fpn_proposals", "generate_proposals",
            "read_file", "decode_jpeg"]
