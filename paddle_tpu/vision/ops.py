"""paddle.vision.ops — detection operators.

Reference: /root/reference/python/paddle/vision/ops.py (nms, roi_align,
roi_pool, box_coder, distribute_fpn_proposals, deform_conv2d, yolo_*)
backed by CUDA kernels. TPU-native: every op is a fixed-shape jnp/lax
composition — NMS is an O(N^2) IoU matrix + lax.fori suppression sweep
(the MXU eats the matrix; no dynamic shapes), RoI align is vectorized
bilinear gather. All differentiable where the reference's are.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply, apply_nodiff

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "PSRoIPool", "RoIAlign", "RoIPool"]


def _iou_matrix(boxes):
    """[N, 4] xyxy → [N, N] IoU."""
    x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] of two xyxy box sets."""
    def f(a, b):
        x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
        y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
        x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
        y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
        inter = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * \
            jnp.maximum(a[:, 3] - a[:, 1], 0)
        area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * \
            jnp.maximum(b[:, 3] - b[:, 1], 0)
        return inter / jnp.maximum(
            area_a[:, None] + area_b[None, :] - inter, 1e-10)
    return apply("box_iou", f, boxes1, boxes2)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k: Optional[int] = None):
    """paddle.vision.ops.nms parity: returns kept indices sorted by
    score. Class-aware when category_idxs given (boxes of different
    classes never suppress each other). Fixed-shape XLA impl: sort by
    score, O(N^2) IoU, sequential suppression via lax.fori_loop."""
    def f(bx, *rest):
        it = iter(rest)
        sc = next(it) if scores is not None else jnp.arange(
            bx.shape[0], 0.0, -1.0)
        cats = next(it) if category_idxs is not None else None
        n = bx.shape[0]
        order = jnp.argsort(-sc)
        b = bx[order]
        iou = _iou_matrix(b)
        if cats is not None:
            c = cats[order]
            same = c[:, None] == c[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            # suppress i if any kept earlier box overlaps it too much
            overlap = (iou[i] > iou_threshold) & keep & \
                (jnp.arange(n) < i)
            return keep.at[i].set(~overlap.any())

        keep = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
        kept_sorted = jnp.where(keep, jnp.arange(n), n)
        sel = jnp.sort(kept_sorted)  # keep score order, pad with n
        idx = order[jnp.minimum(sel, n - 1)]
        valid = sel < n
        count = valid.sum()
        # compact to the front, invalid slots filled with -1
        idx = jnp.where(valid, idx, -1)
        return idx, count

    args = (boxes,) + ((scores,) if scores is not None else ()) + \
        ((category_idxs,) if category_idxs is not None else ())
    idx, count = apply_nodiff("nms", f, *args)
    # host-side compaction to the reference's variable-length result
    arr = np.asarray(idx._value)
    arr = arr[arr >= 0]
    if top_k is not None:
        arr = arr[:top_k]
    return Tensor(jnp.asarray(arr, jnp.int64))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True,
              name=None):
    """paddle.vision.ops.roi_align parity: x [N,C,H,W], boxes [R,4] xyxy
    in input coords, boxes_num [N] rois per image. Bilinear-sampled
    [R, C, oh, ow]; differentiable w.r.t. x.

    sampling_ratio=-1 (adaptive): the reference derives the grid per bin
    as ceil(roi_size/output_size). A data-dependent grid is not a static
    XLA shape, so eager calls size one shared grid for the largest RoI
    (capped at 8x8); under jit tracing this falls back to a fixed 2x2
    grid — a small numeric deviation from the reference for very large
    RoIs. Pass an explicit sampling_ratio for bit-stable behavior."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    if sampling_ratio > 0:
        samp = sampling_ratio
    else:
        # Adaptive: one shared grid sized for the largest RoI (a denser
        # uniform grid over-samples small bins, converging to the same bin
        # integral). Resolved from the user-facing boxes BEFORE any
        # autograd/jit tracing so training and eval agree; falls back to
        # 2x2 under to_static tracing or with zero RoIs.
        # NOTE: reading boxes forces a device→host sync; on eager hot
        # paths pass an explicit sampling_ratio to avoid it.
        samp = 2
        try:
            b = np.asarray(getattr(boxes, "value", boxes), dtype=np.float64)
            if b.shape[0]:
                brw = np.maximum((b[:, 2] - b[:, 0]) * spatial_scale,
                                 1e-3 if aligned else 1.0)
                brh = np.maximum((b[:, 3] - b[:, 1]) * spatial_scale,
                                 1e-3 if aligned else 1.0)
                peak = max(brh.max() / oh, brw.max() / ow)
                if np.isfinite(peak):  # NaN/Inf boxes: keep the 2x2 grid
                    samp = max(1, min(int(np.ceil(peak)), 8))
        except jax.errors.ConcretizationTypeError:
            pass

    if boxes.shape[0] == 0:  # static shape: no RoIs → empty result
        return apply("roi_align",
                     lambda xa, bxs, bn: jnp.zeros(
                         (0, xa.shape[1], oh, ow), xa.dtype),
                     x, boxes, boxes_num)

    def f(xa, bxs, bn):
        n, c, h, w = xa.shape
        r = bxs.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), bn, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = bxs[:, 0] * spatial_scale - off
        y1 = bxs[:, 1] * spatial_scale - off
        x2 = bxs[:, 2] * spatial_scale - off
        y2 = bxs[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        s = samp
        # sample grid: [r, oh, ow, s, s]
        iy = (jnp.arange(s) + 0.5) / s
        ix = (jnp.arange(s) + 0.5) / s
        gy = (y1[:, None, None] + (jnp.arange(oh)[None, :, None]
                                   + iy[None, None, :]) *
              bin_h[:, None, None])           # [r, oh, s]
        gx = (x1[:, None, None] + (jnp.arange(ow)[None, :, None]
                                   + ix[None, None, :]) *
              bin_w[:, None, None])           # [r, ow, s]

        def bilinear(img, yy, xx):
            """img [c,h,w]; yy/xx [...]."""
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = jnp.clip(yy, 0, h - 1) - y0
            wx = jnp.clip(xx, 0, w - 1) - x0
            y0 = y0.astype(jnp.int32)
            x0 = x0.astype(jnp.int32)
            y1i = y1i.astype(jnp.int32)
            x1i = x1i.astype(jnp.int32)
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1i]
            v10 = img[:, y1i, x0]
            v11 = img[:, y1i, x1i]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        def per_roi(ri):
            img = xa[img_idx[ri]]
            yy = gy[ri][:, None, :, None]      # [oh,1,s,1]
            xx = gx[ri][None, :, None, :]      # [1,ow,1,s]
            yy = jnp.broadcast_to(yy, (oh, ow, s, s))
            xx = jnp.broadcast_to(xx, (oh, ow, s, s))
            vals = bilinear(img, yy, xx)       # [c, oh, ow, s, s]
            return vals.mean(axis=(-1, -2))    # [c, oh, ow]

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply("roi_align", f, x, boxes, boxes_num)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
             name=None, _reduce: str = "max"):
    """Max-pool RoI extraction (reference roi_pool): [R, C, oh, ow].
    _reduce='mean' gives the average-pool variant PSRoIPool needs."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def f(xa, bxs, bn):
        n, c, h, w = xa.shape
        r = bxs.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), bn, total_repeat_length=r)
        x1 = jnp.floor(bxs[:, 0] * spatial_scale)
        y1 = jnp.floor(bxs[:, 1] * spatial_scale)
        x2 = jnp.ceil(bxs[:, 2] * spatial_scale)
        y2 = jnp.ceil(bxs[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)

        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def per_roi(ri):
            img = xa[img_idx[ri]]
            # bin id of every pixel (or -1 outside the roi)
            by = jnp.floor((ys - y1[ri]) / rh[ri] * oh)
            bxp = jnp.floor((xs - x1[ri]) / rw[ri] * ow)
            by = jnp.where((ys >= y1[ri]) & (ys < y1[ri] + rh[ri]),
                           jnp.clip(by, 0, oh - 1), -1)
            bxp = jnp.where((xs >= x1[ri]) & (xs < x1[ri] + rw[ri]),
                            jnp.clip(bxp, 0, ow - 1), -1)
            mask = (by[:, None, None, None] ==
                    jnp.arange(oh)[None, None, :, None]) & \
                   (bxp[None, :, None, None] ==
                    jnp.arange(ow)[None, None, None, :])  # [h,w,oh,ow]
            if _reduce == "mean":
                s = jnp.where(mask[None], img[:, :, :, None, None],
                              0.0).sum(axis=(1, 2))
                cnt = mask.sum(axis=(0, 1))
                return s / jnp.maximum(cnt, 1)[None]
            vals = jnp.where(mask[None], img[:, :, :, None, None],
                             -jnp.inf)
            out = vals.max(axis=(1, 2))        # [c, oh, ow]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply("roi_pool", f, x, boxes, boxes_num)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True, axis: int = 0, name=None):
    """SSD-style box encode/decode (reference box_coder). Decode
    supports [N, M, 4] target boxes with priors broadcast along `axis`
    (0: priors along N, 1: priors along M); prior_box_var may be None
    (treated as ones), a 4-vector, or per-box [N, 4]."""
    var_is_none = prior_box_var is None

    def f(pb, tb, *rest):
        pbv = rest[0] if rest else jnp.ones_like(pb)
        if pbv.ndim == 1:
            pbv = jnp.broadcast_to(pbv, pb.shape)
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw * 0.5
        pcy = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw * 0.5
            tcy = tb[:, 1] + th * 0.5
            dx = (tcx - pcx) / pw / pbv[:, 0]
            dy = (tcy - pcy) / ph / pbv[:, 1]
            dw = jnp.log(tw / pw) / pbv[:, 2]
            dh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([dx, dy, dw, dh], axis=1)
        # decode_center_size: broadcast priors across [N, M, 4] targets
        if tb.ndim == 3:
            exp = (slice(None), None) if axis == 0 else (None, slice(None))
            pw, ph, pcx, pcy = (v[exp] for v in (pw, ph, pcx, pcy))
            pbv = pbv[exp + (slice(None),)]
            v0, v1, v2, v3 = (pbv[..., k] for k in range(4))
        else:
            v0, v1, v2, v3 = (pbv[:, k] for k in range(4))
        dcx = v0 * tb[..., 0] * pw + pcx
        dcy = v1 * tb[..., 1] * ph + pcy
        dw = jnp.exp(v2 * tb[..., 2]) * pw
        dh = jnp.exp(v3 * tb[..., 3]) * ph
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm,
                          dcy + dh * 0.5 - norm], axis=-1)

    args = (prior_box, target_box) + \
        (() if var_is_none else (prior_box_var,))
    return apply("box_coder", f, *args)


class RoIAlign:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    """Position-sensitive RoI AVERAGE pooling: input channels = C*oh*ow;
    each output bin averages its own channel group (reference
    psroi_pool, vision/ops.py — 'position-sensitive average pooling')."""

    def __init__(self, output_size, spatial_scale: float = 1.0):
        self.output_size = output_size if isinstance(
            output_size, (tuple, list)) else (output_size, output_size)
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        oh, ow = self.output_size
        pooled = roi_pool(x, boxes, boxes_num, (oh, ow),
                          self.spatial_scale, _reduce="mean")

        def f(p):
            r, c_all, _, _ = p.shape
            c = c_all // (oh * ow)
            p = p.reshape(r, c, oh, ow, oh, ow)
            # bin (i,j) takes channel-group (i,j)
            i = jnp.arange(oh)[:, None]
            j = jnp.arange(ow)[None, :]
            return p[:, :, i, j, i, j]
        return apply("psroi_select", f, pooled)
