"""paddle_tpu.vision — models/transforms/datasets
(parity: /root/reference/python/paddle/vision/)."""
from . import models  # noqa: F401
