from .resnet import *  # noqa: F401,F403
