"""ShuffleNetV2 (parity:
/root/reference/python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat, reshape, split, swapaxes
from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                   MaxPool2D, ReLU, Sequential, Swish)

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
           "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    # tape-recorded ops so gradients flow on the eager backward path
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    return reshape(swapaxes(x, 1, 2), [n, c, h, w])


class ConvBNReLU(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1,
                 act=True):
        layers = [Conv2D(in_c, out_c, kernel, stride=stride,
                         padding=kernel // 2, groups=groups,
                         bias_attr=False),
                  BatchNorm2D(out_c)]
        if act:  # True/'relu' -> ReLU; 'swish' -> Swish
            layers.append(Swish() if act == "swish" else ReLU())
        super().__init__(*layers)


class InvertedResidual(Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = Sequential(
                ConvBNReLU(branch_c, branch_c, 1, act=act),
                ConvBNReLU(branch_c, branch_c, 3, stride, branch_c,
                           act=False),
                ConvBNReLU(branch_c, branch_c, 1, act=act))
        else:
            self.branch1 = Sequential(
                ConvBNReLU(in_c, in_c, 3, stride, in_c, act=False),
                ConvBNReLU(in_c, branch_c, 1, act=act))
            self.branch2 = Sequential(
                ConvBNReLU(in_c, branch_c, 1, act=act),
                ConvBNReLU(branch_c, branch_c, 3, stride, branch_c,
                           act=False),
                ConvBNReLU(branch_c, branch_c, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act='relu', num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        out_channels = {
            0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
            0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
            1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
        }[scale]
        self.conv1 = ConvBNReLU(3, out_channels[0], 3, 2, act=act)
        self.maxpool = MaxPool2D(3, 2, padding=1)
        in_c = out_channels[0]
        stages = []
        for i, repeats in enumerate(stage_repeats):
            out_c = out_channels[i + 1]
            blocks = [InvertedResidual(in_c, out_c, 2, act=act)]
            for _ in range(repeats - 1):
                blocks.append(InvertedResidual(out_c, out_c, 1, act=act))
            stages.append(Sequential(*blocks))
            in_c = out_c
        self.stages = Sequential(*stages)
        self.conv_last = ConvBNReLU(in_c, out_channels[-1], 1, act=act)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(out_channels[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
