"""DenseNet (parity:
/root/reference/python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                   Dropout, Layer, LayerList, Linear, MaxPool2D, ReLU,
                   Sequential)

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseLayer(Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout=0.0):
        super().__init__()
        self.norm1 = BatchNorm2D(in_c)
        self.relu = ReLU()
        self.conv1 = Conv2D(in_c, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3,
                            padding=1, bias_attr=False)
        self.dropout = Dropout(dropout) if dropout > 0 else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class DenseBlock(Layer):
    def __init__(self, num_layers, in_c, growth_rate, bn_size,
                 dropout=0.0):
        super().__init__()
        self.layers = LayerList([
            DenseLayer(in_c + i * growth_rate, growth_rate, bn_size,
                       dropout)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class Transition(Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            BatchNorm2D(in_c), ReLU(),
            Conv2D(in_c, out_c, 1, bias_attr=False),
            AvgPool2D(2, 2))


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        num_init, growth_rate, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv = Sequential(
            Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(num_init), ReLU(), MaxPool2D(3, 2, padding=1))
        blocks = []
        ch = num_init
        for i, n in enumerate(block_cfg):
            blocks.append(DenseBlock(n, ch, growth_rate, bn_size, dropout))
            ch = ch + n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Transition(ch, ch // 2))
                ch = ch // 2
        self.blocks = Sequential(*blocks)
        self.norm = BatchNorm2D(ch)
        self.relu = ReLU()
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.norm(self.blocks(self.conv(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
