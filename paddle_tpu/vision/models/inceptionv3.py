"""InceptionV3 (parity:
/root/reference/python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                   Dropout, Layer, Linear, MaxPool2D, ReLU, Sequential)

__all__ = ["InceptionV3", "inception_v3"]


class ConvBN(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__(
            Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                   bias_attr=False),
            BatchNorm2D(out_c), ReLU())


class InceptionA(Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1x1 = ConvBN(in_c, 64, 1)
        self.b5x5 = Sequential(ConvBN(in_c, 48, 1),
                               ConvBN(48, 64, 5, padding=2))
        self.b3x3dbl = Sequential(ConvBN(in_c, 64, 1),
                                  ConvBN(64, 96, 3, padding=1),
                                  ConvBN(96, 96, 3, padding=1))
        self.bpool = Sequential(AvgPool2D(3, 1, padding=1),
                                ConvBN(in_c, pool_features, 1))

    def forward(self, x):
        return concat([self.b1x1(x), self.b5x5(x), self.b3x3dbl(x),
                       self.bpool(x)], axis=1)


class InceptionB(Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3x3 = ConvBN(in_c, 384, 3, stride=2)
        self.b3x3dbl = Sequential(ConvBN(in_c, 64, 1),
                                  ConvBN(64, 96, 3, padding=1),
                                  ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3x3(x), self.b3x3dbl(x), self.pool(x)],
                      axis=1)


class InceptionC(Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1x1 = ConvBN(in_c, 192, 1)
        self.b7x7 = Sequential(
            ConvBN(in_c, c7, 1),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7x7dbl = Sequential(
            ConvBN(in_c, c7, 1),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.bpool = Sequential(AvgPool2D(3, 1, padding=1),
                                ConvBN(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1x1(x), self.b7x7(x), self.b7x7dbl(x),
                       self.bpool(x)], axis=1)


class InceptionD(Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3x3 = Sequential(ConvBN(in_c, 192, 1),
                               ConvBN(192, 320, 3, stride=2))
        self.b7x7x3 = Sequential(
            ConvBN(in_c, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3x3(x), self.b7x7x3(x), self.pool(x)],
                      axis=1)


class InceptionE(Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1x1 = ConvBN(in_c, 320, 1)
        self.b3x3_1 = ConvBN(in_c, 384, 1)
        self.b3x3_2a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3x3_2b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3x3dbl_1 = Sequential(ConvBN(in_c, 448, 1),
                                    ConvBN(448, 384, 3, padding=1))
        self.b3x3dbl_2a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3x3dbl_2b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bpool = Sequential(AvgPool2D(3, 1, padding=1),
                                ConvBN(in_c, 192, 1))

    def forward(self, x):
        b3 = self.b3x3_1(x)
        b3 = concat([self.b3x3_2a(b3), self.b3x3_2b(b3)], axis=1)
        bd = self.b3x3dbl_1(x)
        bd = concat([self.b3x3dbl_2a(bd), self.b3x3dbl_2b(bd)], axis=1)
        return concat([self.b1x1(x), b3, bd, self.bpool(x)], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            ConvBN(3, 32, 3, stride=2), ConvBN(32, 32, 3),
            ConvBN(32, 64, 3, padding=1), MaxPool2D(3, 2),
            ConvBN(64, 80, 1), ConvBN(80, 192, 3), MaxPool2D(3, 2))
        self.blocks = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160),
            InceptionC(768, 160), InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.drop = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
