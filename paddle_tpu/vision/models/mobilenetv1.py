"""MobileNetV1 (parity:
/root/reference/python/paddle/vision/models/mobilenetv1.py).

Depthwise convs map to XLA's grouped-convolution HLO; on TPU these lower
to the MXU with feature-group count = channels.
"""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                   ReLU, Sequential)

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNLayer(Layer):
    def __init__(self, in_c, out_c, kernel, stride, padding, groups=1):
        super().__init__()
        self.conv = Conv2D(in_c, out_c, kernel, stride=stride,
                           padding=padding, groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(out_c)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class DepthwiseSeparable(Layer):
    def __init__(self, in_c, out_c1, out_c2, num_groups, stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(int(in_c * scale), int(out_c1 * scale), 3,
                              stride, 1, groups=int(num_groups * scale))
        self.pw = ConvBNLayer(int(out_c1 * scale), int(out_c2 * scale),
                              1, 1, 0)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, 2, 1)
        cfg = [  # in, out1, out2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1),
        ]
        self.blocks = Sequential(*[
            DepthwiseSeparable(i, o1, o2, g, s, scale)
            for (i, o1, o2, g, s) in cfg])
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
