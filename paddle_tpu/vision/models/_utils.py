"""Shared helpers for the vision model zoo."""
from __future__ import annotations


def make_divisible(v, divisor=8, min_value=None):
    """Round channel counts to hardware-friendly multiples (the MobileNet
    rule: never round down by more than 10%)."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v
