"""paddle_tpu.audio.datasets — local-file audio datasets (reference:
/root/reference/python/paddle/audio/datasets/ — ESC50, TESS). No-network
environment: readers parse the standard on-disk layouts."""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..io import Dataset
from .backends import load as _load
from .features import MelSpectrogram

__all__ = ["ESC50", "TESS"]


class ESC50(Dataset):
    """ESC-50 environmental sound classification from a local checkout
    (meta/esc50.csv + audio/*.wav; reference audio/datasets/esc50.py).
    mode='train' uses folds != split_fold; 'dev' the held-out fold.
    feat_type: 'raw' waveform or 'melspectrogram'."""

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 split_fold: int = 1, feat_type: str = "raw",
                 archive=None, **feat_kwargs):
        if data_dir is None:
            raise ValueError(
                "data_dir is required (no network in this environment)")
        meta = os.path.join(data_dir, "meta", "esc50.csv")
        rows = [l.rstrip("\n").split(",") for l in
                open(meta, errors="ignore").read().splitlines()[1:]]
        self.files, self.labels = [], []
        for r in rows:
            fname, fold, target = r[0], int(r[1]), int(r[2])
            keep = (fold != split_fold) if mode == "train" \
                else (fold == split_fold)
            if keep:
                self.files.append(os.path.join(data_dir, "audio", fname))
                self.labels.append(target)
        self.feat_type = feat_type
        self._feat = None
        if feat_type == "melspectrogram":
            self._feat = MelSpectrogram(**feat_kwargs)

    def _waveform(self, path):
        wav, sr = _load(path)
        w = np.asarray(wav.numpy() if hasattr(wav, "numpy") else wav,
                       np.float32)
        return w[0] if w.ndim > 1 else w

    def __getitem__(self, idx):
        w = self._waveform(self.files[idx])
        label = np.int64(self.labels[idx])
        if self._feat is not None:
            import paddle_tpu as paddle
            feat = self._feat(paddle.to_tensor(w[None]))
            return np.asarray(feat.numpy()[0]), label
        return w, label

    def __len__(self):
        return len(self.files)


class TESS(Dataset):
    """Toronto Emotional Speech Set from a local directory of
    <...>_<emotion>.wav files (reference audio/datasets/tess.py)."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                "sad"]

    def __init__(self, data_dir: Optional[str] = None, mode: str = "train",
                 n_folds: int = 5, split_fold: int = 1,
                 feat_type: str = "raw", **feat_kwargs):
        if data_dir is None:
            raise ValueError(
                "data_dir is required (no network in this environment)")
        files = []
        for root, _, names in os.walk(data_dir):
            for n in sorted(names):
                if n.lower().endswith(".wav"):
                    files.append(os.path.join(root, n))
        self.files, self.labels = [], []
        for i, f in enumerate(sorted(files)):
            emo = os.path.splitext(os.path.basename(f))[0] \
                .split("_")[-1].lower()
            if emo not in self.EMOTIONS:
                continue
            fold = i % n_folds + 1
            keep = (fold != split_fold) if mode == "train" \
                else (fold == split_fold)
            if keep:
                self.files.append(f)
                self.labels.append(self.EMOTIONS.index(emo))
        self.feat_type = feat_type
        self._feat = MelSpectrogram(**feat_kwargs) \
            if feat_type == "melspectrogram" else None

    def __getitem__(self, idx):
        wav, sr = _load(self.files[idx])
        w = np.asarray(wav.numpy() if hasattr(wav, "numpy") else wav,
                       np.float32)
        w = w[0] if w.ndim > 1 else w
        label = np.int64(self.labels[idx])
        if self._feat is not None:
            import paddle_tpu as paddle
            feat = self._feat(paddle.to_tensor(w[None]))
            return np.asarray(feat.numpy()[0]), label
        return w, label

    def __len__(self):
        return len(self.files)
