"""audio.functional parity
(/root/reference/python/paddle/audio/functional/functional.py:
hz_to_mel, mel_to_hz, mel_frequencies, fft_frequencies,
compute_fbank_matrix, power_to_db, create_dct; window functions in
window.py)."""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk: bool = False):
    scalar = not isinstance(freq, (Tensor, np.ndarray, jnp.ndarray, list))
    f = np.asarray(freq._value if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, mel)
    return float(mel) if scalar else Tensor(jnp.asarray(mel, jnp.float32))


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, (Tensor, np.ndarray, jnp.ndarray, list))
    m = np.asarray(mel._value if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar else Tensor(jnp.asarray(hz, jnp.float32))


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    low = hz_to_mel(float(f_min), htk)
    high = hz_to_mel(float(f_max), htk)
    mels = np.linspace(low, high, n_mels)
    return Tensor(jnp.asarray(
        np.asarray([mel_to_hz(float(m), htk) for m in mels]),
        jnp.float32))


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2,
                               dtype=jnp.float32))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype="float32"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    fft_f = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = np.asarray(
        [mel_to_hz(float(m), htk) for m in np.linspace(
            hz_to_mel(float(f_min), htk), hz_to_mel(float(f_max), htk),
            n_mels + 2)])
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    weights = np.zeros((n_mels, len(fft_f)))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif isinstance(norm, (int, float)):
        norms = np.linalg.norm(weights, ord=norm, axis=1, keepdims=True)
        weights = weights / np.maximum(norms, 1e-10)
    return Tensor(jnp.asarray(weights, jnp.float32))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    x = spect._value if isinstance(spect, Tensor) else jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype="float32"):
    """[n_mels, n_mfcc] DCT-II matrix."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, jnp.float32))


def get_window(window: Union[str, tuple], win_length: int,
               fftbins: bool = True, dtype="float32"):
    """Window functions (reference audio/functional/window.py)."""
    name = window if isinstance(window, str) else window[0]
    M = win_length + (0 if fftbins else -1)
    n = np.arange(win_length)
    denom = max(M, 1)
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * math.pi * n / denom)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * n / denom)
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * n / denom)
             + 0.08 * np.cos(4 * math.pi * n / denom))
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(win_length)
    elif name == "gaussian":
        std = window[1] if isinstance(window, tuple) else 0.4
        w = np.exp(-0.5 * ((n - (win_length - 1) / 2)
                           / (std * (win_length - 1) / 2)) ** 2)
    elif name == "triang":
        w = 1 - np.abs((n - (win_length - 1) / 2) / (win_length / 2))
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w, jnp.float32))
