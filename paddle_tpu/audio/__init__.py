"""paddle_tpu.audio — audio feature extraction.

Reference: /root/reference/python/paddle/audio/ (functional/: hz↔mel,
fbank matrix, dct; features/: Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC layers; backends/ for file IO). Compute rides
paddle_tpu.signal's STFT (XLA-compiled); file IO backends are gated on
optional soundfile (the image ships none — load/save raise with
instructions, info works for WAV via the stdlib wave module).
"""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends
from . import datasets  # noqa: F401

__all__ = ["functional", "features", "backends", "datasets"]
