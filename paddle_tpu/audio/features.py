"""audio.features — feature-extraction layers
(/root/reference/python/paddle/audio/features/layers.py: Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC). STFT rides paddle_tpu.signal
(XLA framed matmul path)."""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..nn.layer.layers import Layer
from .. import signal as _signal
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return apply("spec_power",
                     lambda s: jnp.abs(s) ** self.power, spec)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self.fbank = AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm)

    def forward(self, x):
        spec = self.spectrogram(x)  # [..., freq, time]
        return apply("mel_proj",
                     lambda f, s: jnp.einsum("mf,...ft->...mt", f, s),
                     self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40,
                 n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        self.dct = AF.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.logmel(x)  # [..., n_mels, time]
        return apply("mfcc_dct",
                     lambda d, s: jnp.einsum("mk,...mt->...kt", d, s),
                     self.dct, lm)
