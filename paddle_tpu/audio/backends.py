"""audio.backends — file IO (reference:
/root/reference/python/paddle/audio/backends/: init_backend.py with
wave_backend default, soundfile optional). The image ships no soundfile;
WAV load/save/info work through the stdlib wave module (8/16/24/32-bit
PCM), other formats need soundfile."""
from __future__ import annotations

import wave as _wave
from typing import List, Optional, Tuple

import numpy as np

from ..framework.core import Tensor

__all__ = ["list_available_backends", "get_current_backend", "set_backend",
           "load", "save", "info", "AudioInfo"]

_backend = "wave_backend"


def list_available_backends() -> List[str]:
    out = ["wave_backend"]
    try:
        import soundfile  # noqa: F401
        out.append("soundfile")
    except ImportError:
        pass
    return out


def get_current_backend() -> str:
    return _backend


def set_backend(backend_name: str):
    global _backend
    if backend_name not in list_available_backends():
        raise ValueError(
            f"backend {backend_name!r} not available; "
            f"have {list_available_backends()}")
    _backend = backend_name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath: str) -> AudioInfo:
    if _backend == "soundfile":
        import soundfile as sf
        i = sf.info(filepath)
        bits = {"PCM_U8": 8, "PCM_S8": 8, "PCM_16": 16, "PCM_24": 24,
                "PCM_32": 32, "FLOAT": 32, "DOUBLE": 64}.get(i.subtype, 16)
        return AudioInfo(i.samplerate, i.frames, i.channels, bits,
                         i.subtype)
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(),
                         f.getnchannels(), f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """Returns (waveform [channels, samples] if channels_first, sr)."""
    import jax.numpy as jnp
    if _backend == "soundfile":
        import soundfile as sf
        if normalize:
            dtype = "float32"
        else:
            # native integer width per subtype (PCM_24 promotes to int32,
            # matching soundfile's own convention)
            subtype = sf.info(filepath).subtype
            dtype = "int16" if subtype in ("PCM_16", "PCM_S8",
                                           "PCM_U8") else "int32"
        data, sr = sf.read(filepath, start=frame_offset,
                           frames=num_frames, dtype=dtype,
                           always_2d=True)
        arr = data.T if channels_first else data
        return Tensor(jnp.asarray(arr)), sr
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        f.setpos(min(frame_offset, n))
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
        width = f.getsampwidth()
        ch = f.getnchannels()
    if width == 3:
        # 24-bit PCM: sign-extend each 3-byte little-endian frame to int32
        b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 3)
        data = (b[:, 0].astype(np.int32)
                | (b[:, 1].astype(np.int32) << 8)
                | (b[:, 2].astype(np.int32) << 16))
        data = (data << 8) >> 8  # arithmetic shift sign-extends bit 23
        data = data.reshape(-1, ch)
    else:
        dtype = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype=dtype).reshape(-1, ch)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    import jax.numpy as jnp
    return Tensor(jnp.asarray(arr)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_S",
         bits_per_sample: int = 16):
    if _backend == "soundfile":
        import soundfile as sf
        arr = np.asarray(src._value if isinstance(src, Tensor) else src)
        if channels_first:
            arr = arr.T
        subtype = {16: "PCM_16", 24: "PCM_24", 32: "PCM_32"}.get(
            bits_per_sample, "PCM_16")
        sf.write(filepath, arr, sample_rate, subtype=subtype)
        return
    if bits_per_sample not in (8, 16, 24, 32):
        raise ValueError(
            f"bits_per_sample must be one of 8/16/24/32, "
            f"got {bits_per_sample}")
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T
    width = bits_per_sample // 8
    full = float(2 ** (bits_per_sample - 1))
    if arr.dtype.kind == "f":
        # scale in float64: float32 can't represent 2**31-1 exactly, so
        # full-scale samples would overflow int32 and flip sign
        arr = np.clip(arr.astype(np.float64), -1.0, 1.0)
        arr = np.clip(np.round(arr * (full - 1)),
                      -full, full - 1).astype(np.int32)
    else:
        arr = arr.astype(np.int32)
    if bits_per_sample == 8:
        payload = (arr + 128).astype(np.uint8)  # WAV 8-bit is unsigned
    elif bits_per_sample == 16:
        payload = arr.astype(np.int16)
    elif bits_per_sample == 32:
        payload = arr
    else:  # 24-bit: emit the low 3 little-endian bytes of each int32
        flat = np.ascontiguousarray(arr).astype("<i4")
        payload = flat.view(np.uint8).reshape(-1, 4)[:, :3]
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        f.setsampwidth(width)
        f.setframerate(sample_rate)
        f.writeframes(np.ascontiguousarray(payload).tobytes())
