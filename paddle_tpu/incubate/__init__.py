"""paddle_tpu.incubate — experimental surface
(/root/reference/python/paddle/incubate/): fused transformer ops
(delegating to the Pallas/XLA implementations in paddle_tpu.ops),
functional autograd transforms (jvp/vjp/Jacobian/Hessian — thin, because
jax IS the autograd engine), 2:4 structured sparsity (asp), and extra
optimizers."""
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from . import distributed  # noqa: F401

__all__ = ["nn", "autograd", "asp", "optimizer", "distributed"]
