"""incubate.distributed.models.moe (parity:
/root/reference/python/paddle/incubate/distributed/models/moe/): the
MoELayer itself lives in paddle_tpu.nn (nn.MoELayer); this namespace
carries the MoE training utilities — notably the MoE-aware global-norm
gradient clip."""
from .grad_clip import ClipGradForMOEByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]
