"""MoE-aware global-norm gradient clipping (parity:
/root/reference/python/paddle/incubate/distributed/models/moe/
grad_clip.py:23 ClipGradForMOEByGlobalNorm).

Why the reference needs a special clip: under its rank-local expert
parallelism each rank materializes ONLY its own experts' grads, so the
global norm must be assembled by summing expert-grad norms across the
moe group while normal params' norms are already replicated — mixing the
two without care double- or under-counts.

Why the TPU-native clip is simpler: expert parameters here are GLOBAL
arrays whose expert dim is GSPMD-sharded; their gradient is likewise one
global (sharded) array, so `sum(g**2)` over it already reduces across
expert shards (XLA inserts the psum). One global norm over all params —
expert or not — is exactly correct. This class therefore exists for API
parity and for the is_expert_param bookkeeping, while the math safely
degenerates to ClipGradByGlobalNorm over the union of both groups.
"""
from __future__ import annotations

from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    """Reference-compatible signature: (clip_norm, is_expert_param_func,
    moe_group, group_name). The predicate and group are accepted and
    recorded; the norm itself needs no special casing on TPU (see module
    docstring)."""

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm=clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group

    def partition_norms(self, params, grads):
        """Diagnostic split of the squared global norm into
        (expert_sq, dense_sq) using is_expert_param_func — what the
        reference computes on the way to the combined norm."""
        import jax.numpy as jnp
        pred = self.is_expert_param_func or (lambda p: False)
        ex = dn = jnp.float32(0)
        for p, g in zip(params, grads):
            if g is None:
                continue
            ga = g._value if hasattr(g, "_value") else g
            sq = jnp.sum(jnp.square(ga.astype(jnp.float32)))
            if pred(p):
                ex = ex + sq
            else:
                dn = dn + sq
        return ex, dn
