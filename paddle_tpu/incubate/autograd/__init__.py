"""incubate.autograd — functional differentiation transforms.

Reference: /root/reference/python/paddle/incubate/autograd/ (jvp, vjp,
Jacobian, Hessian over the prim/composite machinery). Here the engine IS
jax: these wrappers adapt Tensor-level callables to jax transforms and
wrap results back. forward_grad/grad prim-mode toggles are no-ops
(everything already lowers to primitives XLA understands).
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, no_grad

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "disable_prim",
           "enable_prim", "prim_enabled"]


def _wrap_fn(func):
    """Tensor-level callable → array-level callable."""
    def fn(*arrays):
        with no_grad():
            out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return fn


def _unwrap_args(xs):
    if isinstance(xs, (tuple, list)):
        return tuple(x._value if isinstance(x, Tensor) else jnp.asarray(x)
                     for x in xs)
    return (xs._value if isinstance(xs, Tensor) else jnp.asarray(xs),)


def _wrap_out(x):
    if isinstance(x, (tuple, list)):
        return tuple(Tensor(e) for e in x)
    return Tensor(x)


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (func(xs), J·v). Parity:
    incubate/autograd/functional.py jvp."""
    arrays = _unwrap_args(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tangents = _unwrap_args(v)
    out, jv = jax.jvp(_wrap_fn(func), arrays, tangents)
    return _wrap_out(out), _wrap_out(jv)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns (func(xs), vᵀ·J). Parity:
    incubate/autograd/functional.py vjp."""
    arrays = _unwrap_args(xs)
    out, vjp_fn = jax.vjp(_wrap_fn(func), *arrays)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = v._value if isinstance(v, Tensor) else \
            tuple(e._value if isinstance(e, Tensor) else jnp.asarray(e)
                  for e in (v if isinstance(v, (tuple, list)) else (v,)))
        if isinstance(out, tuple) and not isinstance(cot, tuple):
            cot = (cot,)
        if not isinstance(out, tuple) and isinstance(cot, tuple):
            cot = cot[0]
    grads = vjp_fn(cot)
    grads = grads[0] if len(grads) == 1 else grads
    return _wrap_out(out), _wrap_out(grads)


class Jacobian:
    """Lazy full Jacobian (parity: incubate/autograd/functional.py
    Jacobian): J[i, j] = d out_i / d in_j, flattened over non-batch dims.
    Index/slice to materialize."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._arrays = _unwrap_args(xs)
        self._single_in = not isinstance(xs, (tuple, list))
        self._is_batched = is_batched
        self._fn = _wrap_fn(func)
        self._mat = None

    def _materialize(self):
        if self._mat is not None:
            return self._mat
        if self._is_batched:
            # per-sample Jacobian via vmap (a plain jacrev over the batched
            # fn would produce the [b, out, b, in] cross-batch Jacobian);
            # argnums covers every input like the unbatched path
            argnums = tuple(range(len(self._arrays)))
            jac = jax.vmap(jax.jacrev(self._fn, argnums=argnums))(
                *self._arrays)
            b = self._arrays[0].shape[0]
            mats = tuple(
                jnp.asarray(j).reshape(
                    b, -1, int(np.prod(a.shape[1:])))
                for j, a in zip(jac, self._arrays))
            self._mat = mats[0] if self._single_in else mats
            return self._mat
        jac = jax.jacrev(self._fn, argnums=tuple(
            range(len(self._arrays))))(*self._arrays)
        if self._single_in:
            jac = jac[0] if isinstance(jac, tuple) else jac
        out_aval = jax.eval_shape(self._fn, *self._arrays)
        o = int(np.prod(out_aval.shape))
        self._mat = jnp.asarray(jac).reshape(
            o, -1) if not isinstance(jac, tuple) else tuple(
            jnp.asarray(j).reshape(o, -1) for j in jac)
        return self._mat

    @property
    def shape(self):
        m = self._materialize()
        return m.shape if not isinstance(m, tuple) else [x.shape for x in m]

    def __getitem__(self, idx):
        m = self._materialize()
        return Tensor(m[idx]) if not isinstance(m, tuple) else \
            tuple(Tensor(x[idx]) for x in m)

    def numpy(self):
        m = self._materialize()
        return np.asarray(m) if not isinstance(m, tuple) else \
            tuple(np.asarray(x) for x in m)


class Hessian:
    """Lazy Hessian of a scalar-output function (parity:
    incubate/autograd/functional.py Hessian)."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._arrays = _unwrap_args(xs)
        if len(self._arrays) > 1:
            raise NotImplementedError(
                "Hessian over multiple inputs: concatenate them into one "
                "tensor (the reference's Hessian is single-input too)")
        self._fn = _wrap_fn(func)
        self._is_batched = is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            if self._is_batched:
                h = jax.vmap(jax.hessian(self._fn))(*self._arrays)
                b = self._arrays[0].shape[0]
                k = int(np.prod(self._arrays[0].shape[1:]))
                self._mat = jnp.asarray(h).reshape(b, k, k)
            else:
                n = int(np.prod(self._arrays[0].shape))
                h = jax.hessian(self._fn)(*self._arrays)
                self._mat = jnp.asarray(h).reshape(n, n)
        return self._mat

    @property
    def shape(self):
        return self._materialize().shape

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])

    def numpy(self):
        return np.asarray(self._materialize())


# prim mode delegates to the real decomposition registry (round 4 —
# closes SURVEY §2.1 "decomposition registry" partial): enabling routes
# decomposable ops through primitive-only rules at the apply() seam.
from ...decomposition import disable_prim, enable_prim, prim_enabled  # noqa: E402,F401
