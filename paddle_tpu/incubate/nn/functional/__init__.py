"""incubate.nn.functional — fused-op API parity
(/root/reference/python/paddle/incubate/nn/functional/: fused_rms_norm,
fused_layer_norm, fused_rotary_position_embedding, fused_bias_act,
fused_linear, ...). On TPU the fusion itself is XLA's job (plus the
Pallas flash-attention kernel in paddle_tpu/ops); these wrappers keep
the reference's fused-op call signatures so incubate users can port
unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ....framework.core import Tensor, apply  # type: ignore
# package depth: paddle_tpu/incubate/nn/functional → framework is 3 up

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "fused_bias_act", "fused_linear", "fused_linear_activation",
    "fused_dropout_add", "swiglu", "fused_multi_head_attention",
    "fused_feedforward", "variable_length_memory_efficient_attention",
]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    """fused_rms_norm parity (incubate/nn/functional/fused_rms_norm.py)."""
    from ....ops.rms_norm import rms_norm  # array-level kernel

    if norm_weight is not None:
        out = apply("rms_norm",
                    lambda xa, wa: rms_norm(xa, wa, epsilon,
                                            axis=begin_norm_axis),
                    x, norm_weight)
    else:
        out = apply("rms_norm",
                    lambda xa: rms_norm(xa, None, epsilon,
                                        axis=begin_norm_axis), x)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    from ....nn import functional as F
    shape = tuple(x.shape[begin_norm_axis:]) if begin_norm_axis != -1 \
        else (x.shape[-1],)
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    **kwargs):
    """Parity: incubate/nn/functional/fused_rotary_position_embedding.py —
    returns (q, k, v) with rotary applied to q/k (v passes through)."""
    from ....ops.rope import apply_rotary_pos_emb  # array-level kernel

    def f(qa, ka, *rest):
        it = iter(rest)
        cos_a = next(it) if cos is not None else None
        sin_a = next(it) if sin is not None else None
        pos_a = next(it) if position_ids is not None else None
        return apply_rotary_pos_emb(qa, ka, cos_a, sin_a, pos_a)

    extra = tuple(a for a in (cos, sin, position_ids) if a is not None)
    q2, k2 = apply("fused_rope", f, q, k if k is not None else q, *extra)
    return q2, (k2 if k is not None else None), v


def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    from ....nn import functional as F
    if bias is not None:
        x = x + bias
    act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
           "swiglu": swiglu}.get(act_method)
    if act is None:
        raise ValueError(f"unsupported act_method {act_method!r}")
    return act(x)


def swiglu(x, y=None):
    """SwiGLU: silu(x) * y; single-arg form splits the last dim."""
    from ....nn import functional as F
    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jnp.multiply(a1 * (1 / (1 + jnp.exp(-a1))), a2)
        return apply("swiglu", f, x)
    return F.silu(x) * y


def fused_linear(x, weight, bias=None, transpose_weight=False, **kwargs):
    def f(xa, wa, *rest):
        w = wa.T if transpose_weight else wa
        out = xa @ w
        if rest:
            out = out + rest[0]
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply("fused_linear", f, *args)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    def f(xa, ya, *rest):
        a = xa.T if trans_x else xa
        b = ya.T if trans_y else ya
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = (x, y) + ((bias,) if bias is not None else ())
    out = apply("fused_linear_act", f, *args)
    from ....nn import functional as F
    return {"gelu": F.gelu, "relu": F.relu, "": lambda v: v,
            None: lambda v: v}[activation](out)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      **kwargs):
    from ....nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None, **kwargs):
    """Whole fused-MHA block parity (fused_transformer.py:
    fused_multi_head_attention). qkv_weight: [3, H, D/H, D] layout like
    the reference."""
    from ....nn import functional as F
    from ....nn.functional.attention import flash_attention

    residual = x
    if pre_layer_norm:
        x = fused_layer_norm(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    three, h, hd, d = qkv_weight.shape
    w = qkv_weight.reshape([3 * h * hd, d])

    def qkv_f(xa, wa, *rest):
        out = xa @ wa.T
        if rest:
            out = out + rest[0].reshape(-1)
        return out
    args = (x, w) + ((qkv_bias,) if qkv_bias is not None else ())
    qkv = apply("fused_qkv", qkv_f, *args)
    b, s = qkv.shape[0], qkv.shape[1]
    qkv = qkv.reshape([b, s, 3, h, hd])
    q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
    if attn_mask is not None:
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
    else:
        out, _ = flash_attention(
            q, k, v, dropout=attn_dropout_rate if training else 0.0)
    out = out.reshape([b, s, h * hd])
    out = F.linear(out, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, **kwargs):
    """fused_feedforward parity (fused_transformer.py)."""
    from ....nn import functional as F
    residual = x
    if pre_layer_norm:
        x = fused_layer_norm(x, ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Varlen attention parity (reference binds a CUDA kernel;
    here the Pallas/XLA flash path with a length mask)."""
    from ....nn import functional as F
    if mask is not None:
        return F.scaled_dot_product_attention(query, key, value,
                                              attn_mask=mask,
                                              is_causal=causal)
    from ....nn.functional.attention import flash_attention
    out, _ = flash_attention(query, key, value, causal=causal)
    return out


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """Reference incubate fused_matmul_bias (cublasLt epilogue): one
    XLA dot + add — the fusion happens in the compiler."""
    from ....tensor.linalg import matmul as _mm
    out = _mm(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + bias
    return out


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """Reference fused op: layer_norm(residual + dropout(x + bias)).
    One composition; XLA fuses the elementwise chain into the norm."""
    from ....nn import functional as F
    h = x if bias is None else x + bias
    h = F.dropout(h, dropout_rate, training=training, mode=mode)
    h = residual + h
    d = h.shape[-1]
    return F.layer_norm(h, [d], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """Reference fused_ec_moe (expert-choice MoE FFN). GShard dispatch/
    combine (ops/moe.py topk_gating) with per-expert biased FFN:
    act(x@w1 + b1) @ w2 + b2, weights [E, D, H] / [E, H, D], biases
    [E, 1, H] / [E, 1, D]."""
    import jax
    import jax.numpy as jnp
    from ....framework.core import Tensor, apply
    from ....ops.moe import topk_gating
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[act_type]

    def f(xa, gw, w1, b1, w2, b2):
        b, s, d = xa.shape
        tokens = xa.reshape(b * s, d)
        e = w1.shape[0]
        capacity = -(-2 * tokens.shape[0] // e // 8) * 8
        logits = tokens.astype(jnp.float32) @ gw.astype(jnp.float32)
        dispatch, combine, aux, stats = topk_gating(logits, 2, capacity)
        ein = jnp.einsum("tec,td->ecd", dispatch.astype(xa.dtype), tokens)
        h = act(jnp.einsum("ecd,edh->ech", ein, w1.astype(xa.dtype))
                + b1.reshape(e, 1, -1).astype(xa.dtype))
        eout = jnp.einsum("ech,ehd->ecd", h, w2.astype(xa.dtype)) \
            + b2.reshape(e, 1, -1).astype(xa.dtype)
        # bias must only reach tokens actually routed to a slot
        slot_used = dispatch.sum(axis=0).astype(xa.dtype)[..., None]
        eout = eout * jnp.minimum(slot_used, 1.0)
        out = jnp.einsum("tec,ecd->td", combine.astype(xa.dtype), eout)
        return out.reshape(b, s, d)
    return apply("fused_ec_moe", f, x, gate, bmm0_weight, bmm0_bias,
                 bmm1_weight, bmm1_bias)


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None,
                               out_smooth=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default",
                               out_scale=-1, quant_round_type=1,
                               quant_max_bound=127.0,
                               quant_min_bound=-127.0, name=None):
    """Reference masked_multihead_attention: single-token decode
    attention over a [2, B, H, MaxLen, D] cache. The serving engine's
    paged path (inference.ServingEngine) is the production form; this
    wrapper implements the dense-cache reference semantics for API
    parity."""
    import jax.numpy as jnp
    from ....framework.core import Tensor, apply

    if cache_kv is None:
        raise ValueError("masked_multihead_attention needs cache_kv "
                         "[2, batch, heads, max_len, head_dim]")

    def f(qkv, cache, *maybe_seq):
        # qkv: [B, 3*H*D] single decode token
        _, b, h, max_len, d = cache.shape
        q, k, v = jnp.split(qkv.reshape(b, 3, h, d), 3, axis=1)
        q, k, v = q[:, 0], k[:, 0], v[:, 0]          # [b, h, d]
        if maybe_seq:
            pos = maybe_seq[0].reshape(b)
        else:
            pos = jnp.zeros((b,), jnp.int32)
        bi = jnp.arange(b)[:, None]
        hi = jnp.arange(h)[None, :]
        cache = cache.at[0, bi, hi, pos[:, None]].set(k)
        cache = cache.at[1, bi, hi, pos[:, None]].set(v)
        ks, vs = cache[0], cache[1]                   # [b, h, L, d]
        s = jnp.einsum("bhd,bhld->bhl", q.astype(jnp.float32),
                       ks.astype(jnp.float32)) / jnp.sqrt(float(d))
        mask = jnp.arange(max_len)[None, None, :] <= pos[:, None, None]
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhl,bhld->bhd", p, vs.astype(jnp.float32))
        return o.reshape(b, h * d).astype(qkv.dtype), cache

    import jax
    args = [x, cache_kv] + ([sequence_lengths]
                            if sequence_lengths is not None else [])
    out, new_cache = apply("masked_mha", f, *args)
    return out, new_cache


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None,
        pre_value_cache=None, cache_k_quant_scales=None,
        cache_v_quant_scales=None, cache_k_dequant_scales=None,
        cache_v_dequant_scales=None, qkv_out_scale=None, qkv_bias=None,
        out_shift=None, out_smooth=None, max_enc_len_this_time=None,
        max_dec_len_this_time=None, rope_emb=None, mask=None,
        tgt_mask=None, max_input_length=-1, block_size=64,
        use_neox_style=False, **kwargs):
    """Reference block_multihead_attention (the paged-KV serving
    kernel). The TPU-native implementation is ops.paged_attention
    (Pallas scalar-prefetch decode kernel) driven by
    inference.ServingEngine; this wrapper exposes the decode step for
    API parity: qkv [B, 3*H*D] one token per sequence, caches
    [num_blocks, kv_heads, block_size, head_dim]."""
    import jax.numpy as jnp
    from ....framework.core import Tensor, apply
    from ....ops.paged_attention import (paged_attention_decode,
                                         reshape_and_cache)

    def f(qkv_a, kc, vc, tables, dec_lens):
        nb, kvh, bs, d = kc.shape
        b = qkv_a.shape[0]
        h = qkv_a.shape[1] // (3 * d)
        q, k, v = jnp.split(qkv_a.reshape(b, 3, h, d), 3, axis=1)
        q, k, v = q[:, 0], k[:, 0, :kvh], v[:, 0, :kvh]
        ctx = dec_lens.reshape(b).astype(jnp.int32)
        # this token's slot: position ctx within the sequence's table
        blk = jnp.take_along_axis(tables, (ctx // bs)[:, None],
                                  axis=1)[:, 0]
        slots = blk * bs + ctx % bs
        kc, vc = reshape_and_cache(k, v, kc, vc, slots)
        out = paged_attention_decode(q, kc, vc, tables, ctx + 1)
        return out.reshape(b, h * d), kc, vc

    out, kc, vc = apply("block_mha", f, qkv, key_cache, value_cache,
                        block_tables, seq_lens_decoder)
    return out, kc, vc


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, time_step=None, attn_mask=None,
        dropout_rate=0.0, activation="gelu", training=False, mode=None,
        trans_qkvw=True, ring_id=-1, name=None):
    """Reference fused_multi_transformer: N pre-LN transformer layers in
    one call (the serving fast path). Composed from the existing fused
    primitives — XLA fuses within each layer.

    Homogeneous stacks (same weight shapes every layer, all biases
    present, pre-LN, no dropout, no KV cache) take a scan-over-layers
    path: weights stack to [L, ...] and ONE compiled layer body runs
    under lax.scan, so compile time is depth-independent (the r3 note
    flagged the unrolled loop as a compile-time liability for deep
    serving stacks). Heterogeneous/cached calls keep the unrolled
    trace."""
    from ....nn import functional as F
    h = x
    n_layers = len(qkv_weights)

    def _full(ws):
        return (ws is not None and len(ws) == n_layers
                and all(w is not None for w in ws))

    def _same_shapes(ws):
        s0 = tuple(ws[0].shape)
        return all(tuple(w.shape) == s0 for w in ws)

    scan_ok = (
        cache_kvs is None and time_step is None and dropout_rate == 0.0
        and pre_layer_norm and n_layers > 1
        and activation in ("gelu", "relu", "silu")
        and all(_full(ws) and _same_shapes(ws) for ws in (
            ln_scales, ln_biases, qkv_weights, qkv_biases,
            linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
            ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases)))
    if scan_ok:
        return _fused_multi_transformer_scan(
            x, ln_scales, ln_biases, qkv_weights, qkv_biases,
            linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
            ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
            epsilon, attn_mask, activation)
    for i in range(n_layers):
        h = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            pre_ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, ln_epsilon=epsilon)
        h = fused_feedforward(
            h, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=pre_layer_norm)
    return h


def _fused_multi_transformer_scan(x, ln_scales, ln_biases, qkv_weights,
                                  qkv_biases, linear_weights,
                                  linear_biases, ffn_ln_scales,
                                  ffn_ln_biases, ffn1_weights,
                                  ffn1_biases, ffn2_weights, ffn2_biases,
                                  epsilon, attn_mask, activation):
    """One taped op: [L, ...]-stacked weights scanned by a single
    compiled pre-LN layer body (numerics match the unrolled path —
    tests/test_incubate.py parity test)."""
    import jax
    import jax.numpy as jnp
    from ....framework.core import apply
    from ....ops.flash_attention import flash_attention as _fa_arr

    # match nn.functional's variants exactly (F.gelu is the erf form,
    # approximate=False — jax.nn.gelu defaults to tanh-approximate)
    act = {"gelu": lambda a: jax.nn.gelu(a, approximate=False),
           "relu": jax.nn.relu, "silu": jax.nn.silu}[activation]
    mask_args = () if attn_mask is None else (attn_mask,)

    def scan_fn(xa, s1, b1, qw, qb, lw, lb, s2, b2, w1, f1b, w2, f2b,
                *mask):
        m = mask[0] if mask else None

        def ln(z, sc, bi):
            # f32 statistics like F.layer_norm (bf16 stacks must not
            # change numerics when they switch to the scan path)
            z32 = z.astype(jnp.float32)
            mu = z32.mean(-1, keepdims=True)
            var = ((z32 - mu) ** 2).mean(-1, keepdims=True)
            zn = (z32 - mu) / jnp.sqrt(var + epsilon)
            return (zn * sc.astype(jnp.float32)
                    + bi.astype(jnp.float32)).astype(z.dtype)

        def layer(h, ws):
            (ls1, lb1, qw_, qb_, lw_, lbb, ls2, lb2, w1_, b1_, w2_,
             b2_) = ws
            hn = ln(h, ls1, lb1)
            three, nh, hd, d = qw_.shape
            qkv = hn @ qw_.reshape(3 * nh * hd, d).T + qb_.reshape(-1)
            b_, s_ = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape(b_, s_, 3, nh, hd)
            o = _fa_arr(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                        attn_mask=m)
            o = o.reshape(b_, s_, nh * hd) @ lw_ + lbb
            h = h + o
            hn2 = ln(h, ls2, lb2)
            f = act(hn2 @ w1_ + b1_) @ w2_ + b2_
            return h + f, None

        out, _ = jax.lax.scan(
            layer, xa, (s1, b1, qw, qb, lw, lb, s2, b2, w1, f1b, w2,
                        f2b))
        return out

    # stacking all 12xL weight lists is an O(parameter-bytes) copy —
    # for the SERVING case (every weight frozen) cache it keyed on the
    # source ARRAY identities (jax arrays are immutable, and the cache
    # holds references so the ids stay valid): a decode loop calling
    # every step stacks once. Trainable weights are NEVER cached — the
    # stacked Tensors carry the tape linkage of the call that built
    # them (a stale cache would silently drop weight grads), and each
    # optimizer step changes the arrays anyway (zero hits, pinned
    # stale generations).
    from ....tensor.manipulation import stack
    lists = (ln_scales, ln_biases, qkv_weights, qkv_biases,
             linear_weights, linear_biases, ffn_ln_scales,
             ffn_ln_biases, ffn1_weights, ffn1_biases, ffn2_weights,
             ffn2_biases)
    cacheable = all(w.stop_gradient for ws in lists for w in ws)
    if not cacheable:
        stacked = tuple(stack(list(ws)) for ws in lists)
    else:
        key = tuple(id(w._value) for ws in lists for w in ws)
        cached = _FMT_STACK_CACHE.get(key)
        if cached is None:
            stacked = tuple(stack(list(ws)) for ws in lists)
            # never cache tracer-backed stacks: a first call under
            # jit/to_static tracing would otherwise leak its tracers
            # into later eager calls (UnexpectedTracerError)
            concrete = not any(
                isinstance(t._value, jax.core.Tracer)
                for t in stacked)
            if concrete:
                refs = tuple(w._value for ws in lists for w in ws)
                while len(_FMT_STACK_CACHE) >= 4:
                    _FMT_STACK_CACHE.pop(next(iter(_FMT_STACK_CACHE)))
                _FMT_STACK_CACHE[key] = (stacked, refs)
        else:
            stacked = cached[0]

    return apply("fused_multi_transformer_scan", scan_fn, x, *stacked,
                 *mask_args)


# the cache holds a full stacked copy of the weights (plus refs that
# keep the source arrays' ids valid) for up to 4 weight sets; when
# swapping large serving models, call the clear below to release the
# old model's HBM instead of waiting for eviction
_FMT_STACK_CACHE: dict = {}


def clear_fused_multi_transformer_cache():
    """Release the scan-path stacked-weight cache (serving model swap)."""
    _FMT_STACK_CACHE.clear()


__all__ += ["fused_matmul_bias", "fused_bias_dropout_residual_layer_norm",
            "fused_ec_moe", "masked_multihead_attention",
            "block_multihead_attention", "fused_multi_transformer",
            "clear_fused_multi_transformer_cache"]
