"""incubate.nn.functional — fused-op API parity
(/root/reference/python/paddle/incubate/nn/functional/: fused_rms_norm,
fused_layer_norm, fused_rotary_position_embedding, fused_bias_act,
fused_linear, ...). On TPU the fusion itself is XLA's job (plus the
Pallas flash-attention kernel in paddle_tpu/ops); these wrappers keep
the reference's fused-op call signatures so incubate users can port
unchanged.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ....framework.core import Tensor, apply  # type: ignore
# package depth: paddle_tpu/incubate/nn/functional → framework is 3 up

__all__ = [
    "fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
    "fused_bias_act", "fused_linear", "fused_linear_activation",
    "fused_dropout_add", "swiglu", "fused_multi_head_attention",
    "fused_feedforward", "variable_length_memory_efficient_attention",
]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kwargs):
    """fused_rms_norm parity (incubate/nn/functional/fused_rms_norm.py)."""
    from ....ops.rms_norm import rms_norm  # array-level kernel

    if norm_weight is not None:
        out = apply("rms_norm",
                    lambda xa, wa: rms_norm(xa, wa, epsilon,
                                            axis=begin_norm_axis),
                    x, norm_weight)
    else:
        out = apply("rms_norm",
                    lambda xa: rms_norm(xa, None, epsilon,
                                        axis=begin_norm_axis), x)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, **kwargs):
    from ....nn import functional as F
    shape = tuple(x.shape[begin_norm_axis:]) if begin_norm_axis != -1 \
        else (x.shape[-1],)
    return F.layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    **kwargs):
    """Parity: incubate/nn/functional/fused_rotary_position_embedding.py —
    returns (q, k, v) with rotary applied to q/k (v passes through)."""
    from ....ops.rope import apply_rotary_pos_emb  # array-level kernel

    def f(qa, ka, *rest):
        it = iter(rest)
        cos_a = next(it) if cos is not None else None
        sin_a = next(it) if sin is not None else None
        pos_a = next(it) if position_ids is not None else None
        return apply_rotary_pos_emb(qa, ka, cos_a, sin_a, pos_a)

    extra = tuple(a for a in (cos, sin, position_ids) if a is not None)
    q2, k2 = apply("fused_rope", f, q, k if k is not None else q, *extra)
    return q2, (k2 if k is not None else None), v


def fused_bias_act(x, bias=None, act_method="gelu", **kwargs):
    from ....nn import functional as F
    if bias is not None:
        x = x + bias
    act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu,
           "swiglu": swiglu}.get(act_method)
    if act is None:
        raise ValueError(f"unsupported act_method {act_method!r}")
    return act(x)


def swiglu(x, y=None):
    """SwiGLU: silu(x) * y; single-arg form splits the last dim."""
    from ....nn import functional as F
    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jnp.multiply(a1 * (1 / (1 + jnp.exp(-a1))), a2)
        return apply("swiglu", f, x)
    return F.silu(x) * y


def fused_linear(x, weight, bias=None, transpose_weight=False, **kwargs):
    def f(xa, wa, *rest):
        w = wa.T if transpose_weight else wa
        out = xa @ w
        if rest:
            out = out + rest[0]
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return apply("fused_linear", f, *args)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    def f(xa, ya, *rest):
        a = xa.T if trans_x else xa
        b = ya.T if trans_y else ya
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    args = (x, y) + ((bias,) if bias is not None else ())
    out = apply("fused_linear_act", f, *args)
    from ....nn import functional as F
    return {"gelu": F.gelu, "relu": F.relu, "": lambda v: v,
            None: lambda v: v}[activation](out)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      **kwargs):
    from ....nn import functional as F
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None, **kwargs):
    """Whole fused-MHA block parity (fused_transformer.py:
    fused_multi_head_attention). qkv_weight: [3, H, D/H, D] layout like
    the reference."""
    from ....nn import functional as F
    from ....nn.functional.attention import flash_attention

    residual = x
    if pre_layer_norm:
        x = fused_layer_norm(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    three, h, hd, d = qkv_weight.shape
    w = qkv_weight.reshape([3 * h * hd, d])

    def qkv_f(xa, wa, *rest):
        out = xa @ wa.T
        if rest:
            out = out + rest[0].reshape(-1)
        return out
    args = (x, w) + ((qkv_bias,) if qkv_bias is not None else ())
    qkv = apply("fused_qkv", qkv_f, *args)
    b, s = qkv.shape[0], qkv.shape[1]
    qkv = qkv.reshape([b, s, 3, h, hd])
    q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
    if attn_mask is not None:
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
    else:
        out, _ = flash_attention(
            q, k, v, dropout=attn_dropout_rate if training else 0.0)
    out = out.reshape([b, s, h * hd])
    out = F.linear(out, linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, p=dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, **kwargs):
    """fused_feedforward parity (fused_transformer.py)."""
    from ....nn import functional as F
    residual = x
    if pre_layer_norm:
        x = fused_layer_norm(x, ln1_scale, ln1_bias, ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, p=dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        h = F.dropout(h, p=dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = fused_layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Varlen attention parity (reference binds a CUDA kernel;
    here the Pallas/XLA flash path with a length mask)."""
    from ....nn import functional as F
    if mask is not None:
        return F.scaled_dot_product_attention(query, key, value,
                                              attn_mask=mask,
                                              is_causal=causal)
    from ....nn.functional.attention import flash_attention
    out, _ = flash_attention(query, key, value, causal=causal)
    return out
