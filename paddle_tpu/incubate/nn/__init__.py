"""incubate.nn — fused layers (reference:
/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward, FusedMultiHeadAttention, FusedTransformerEncoderLayer,
)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]
