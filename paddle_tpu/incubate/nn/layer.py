"""Fused transformer layers (reference:
/root/reference/python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer).
Parameter layouts match the reference (qkv [3, H, D/H, D]) so state
dicts port; compute routes through incubate.nn.functional.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...framework.core import Parameter
from ...framework import dtype as dtypes
from ...nn.layer.layers import Layer
from ...framework.core import default_generator
import jax

from . import functional as IF


def _xavier(shape, dtype):
    fan_in = shape[-1] if len(shape) > 1 else shape[0]
    fan_out = shape[0] if len(shape) > 1 else shape[0]
    std = math.sqrt(2.0 / (fan_in + fan_out))
    k = default_generator.next_key()
    return std * jax.random.normal(k, shape, dtypes.convert_dtype(dtype))


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        d = "float32"
        self.qkv_weight = Parameter(_xavier(
            (3, num_heads, self.head_dim, embed_dim), d))
        self.qkv_bias = Parameter(jnp.zeros(
            (3, num_heads, self.head_dim), jnp.float32))
        self.linear_weight = Parameter(_xavier((embed_dim, embed_dim), d))
        self.linear_bias = Parameter(jnp.zeros(embed_dim, jnp.float32))
        self.pre_ln_scale = Parameter(jnp.ones(embed_dim, jnp.float32))
        self.pre_ln_bias = Parameter(jnp.zeros(embed_dim, jnp.float32))
        self.ln_scale = Parameter(jnp.ones(embed_dim, jnp.float32))
        self.ln_bias = Parameter(jnp.zeros(embed_dim, jnp.float32))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self.epsilon, training=self.training,
            num_heads=self.num_heads)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate \
            if act_dropout_rate is not None else dropout_rate
        self.epsilon = epsilon
        d = "float32"
        self.linear1_weight = Parameter(_xavier(
            (d_model, dim_feedforward), d))
        self.linear1_bias = Parameter(jnp.zeros(dim_feedforward,
                                                jnp.float32))
        self.linear2_weight = Parameter(_xavier(
            (dim_feedforward, d_model), d))
        self.linear2_bias = Parameter(jnp.zeros(d_model, jnp.float32))
        self.ln1_scale = Parameter(jnp.ones(d_model, jnp.float32))
        self.ln1_bias = Parameter(jnp.zeros(d_model, jnp.float32))
        self.ln2_scale = Parameter(jnp.ones(d_model, jnp.float32))
        self.ln2_bias = Parameter(jnp.zeros(d_model, jnp.float32))

    def forward(self, src, cache=None):
        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, ln1_epsilon=self.epsilon,
            ln2_epsilon=self.epsilon,
            pre_layer_norm=self.normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kwargs):
        super().__init__()
        self.self_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate
            if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.self_attn(src, attn_mask=src_mask)
        return self.ffn(out)
