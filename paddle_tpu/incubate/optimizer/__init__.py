"""incubate.optimizer — LookAhead, ModelAverage, DistributedFusedLamb
(reference: /root/reference/python/paddle/incubate/optimizer/)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...optimizer.optimizer import Lamb as _Lamb

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class DistributedFusedLamb(_Lamb):
    """Sharded LAMB (reference
    /root/reference/python/paddle/incubate/optimizer/distributed_fused_lamb.py).

    The reference fuses every parameter into aligned flat buffers,
    shards the optimizer states over the data-parallel group, and
    hand-schedules the allreduce/clip pipeline. The TPU-native
    equivalent leans on GSPMD: parameters (and their f32 masters /
    moments, which inherit each param's sharding through zeros_like)
    may live sharded across the mesh, the per-layer trust-ratio and
    global-norm reductions auto-insert psum over sharded dims inside
    jit, and XLA fuses the update chain — so `alignment`,
    `nproc_per_node` and `use_hierarchical_allreduce` are layout/comm
    strategy knobs with no TPU meaning (accepted, numerically
    irrelevant, ignored; documented here rather than warned since the
    semantics are exact).

    Honored semantics:
    - is_grad_scaled_by_nranks=False: incoming grads are global SUMS
      (reference: allreduce without mean) and are divided by the data-
      parallel world size before use.
    - use_master_param_norm=False: trust-ratio norms are computed over
      the low-precision weights instead of the f32 masters.
    - gradient_accumulation_steps=k: step() accumulates k micro-grads
      (in f32 when use_master_acc_grad, else grad dtype) and applies
      one LAMB update on their mean every k-th call. (Inside a
      jit.TrainStep prefer strategy.gradient_merge — the compiled
      equivalent.)
    - clip_after_allreduce=False is unimplementable here: grads are
      globally reduced before any host code sees them (single-
      controller GSPMD), so pre-allreduce clipping has no seam — a
      loud error, not a silent re-ordering.
    """

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        if not clip_after_allreduce:
            raise NotImplementedError(
                "clip_after_allreduce=False (clip each rank's local grad "
                "before the allreduce) has no seam under single-"
                "controller GSPMD — grads are globally reduced before "
                "the optimizer runs; use the default True")
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=
                         exclude_from_weight_decay_fn,
                         name=name, multi_precision=True)
        self._use_master_param_norm = bool(use_master_param_norm)
        self._grad_is_scaled = bool(is_grad_scaled_by_nranks)
        self._acc_k = max(1, int(gradient_accumulation_steps))
        self._acc_f32 = bool(use_master_acc_grad)
        self._acc = None
        self._acc_n = 0

    def _trust_norm_source(self, mp, p):
        if self._use_master_param_norm:
            return mp
        return mp.astype(p.dtype).astype(mp.dtype)

    def _grad_divisor(self) -> float:
        if self._grad_is_scaled:
            return 1.0
        from ...distributed import get_world_size
        return float(max(1, get_world_size()))

    def _step_with_scaled_grads(self, get_grad):
        """Run one LAMB step with each param's grad temporarily replaced
        by get_grad(i, p) (None = leave as-is); restores on exit."""
        params = self._parameter_list
        saved = [p.grad for p in params]
        try:
            for i, p in enumerate(params):
                g = get_grad(i, p)
                if g is not None:
                    p.grad = Tensor(g)
            super().step()
        finally:
            for p, s in zip(params, saved):
                p.grad = s

    def step(self):
        div = self._grad_divisor() * self._acc_k
        params = self._parameter_list
        if self._acc_k > 1:
            if self._acc is None:
                self._acc = [None] * len(params)
            self._acc_n += 1
            for i, p in enumerate(params):
                if p.grad is None:
                    continue
                g = p.grad._value
                if self._acc_f32:
                    g = g.astype(jnp.float32)
                self._acc[i] = g if self._acc[i] is None \
                    else self._acc[i] + g
            if self._acc_n < self._acc_k:
                return          # caller clear_grad()s between micros
            try:
                self._step_with_scaled_grads(
                    lambda i, p: None if self._acc[i] is None
                    else self._acc[i] / div)
            finally:
                self._acc = None
                self._acc_n = 0
        elif div != 1.0:
            self._step_with_scaled_grads(
                lambda i, p: None if p.grad is None
                else p.grad._value / div)
        else:
            super().step()


class LookAhead:
    """Lookahead wrapper (reference incubate/optimizer/lookahead.py):
    every k steps, slow weights ← slow + alpha*(fast - slow); fast ←
    slow."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_num = 0
        self._slow = None

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        params = self.inner_optimizer._parameter_list
        if self._slow is None:
            self._slow = [jnp.array(p._value) for p in params]
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p._value -
                                                     self._slow[i])
                self._slow[i] = slow
                p._replace(slow)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        if self._slow is not None:
            sd["slow_params"] = [np.asarray(s) for s in self._slow]
        sd["lookahead_step"] = self._step_num
        return sd


class ModelAverage:
    """Running average of parameters for eval (reference
    incubate/optimizer/modelaverage.py): apply()/restore() swap averaged
    weights in and out."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params: List = list(parameters or [])
        self._sum = None
        self._count = 0
        self._backup = None

    def step(self):
        if self._sum is None:
            self._sum = [jnp.array(p._value) for p in self._params]
            self._count = 1
        else:
            self._sum = [s + p._value
                         for s, p in zip(self._sum, self._params)]
            self._count += 1

    def apply(self, executor=None, need_restore: bool = True):
        if self._sum is None:
            return
        self._backup = [jnp.array(p._value) for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._replace(s / self._count)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._replace(b)
        self._backup = None
