"""incubate.optimizer — LookAhead, ModelAverage (reference:
/root/reference/python/paddle/incubate/optimizer/)."""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """Lookahead wrapper (reference incubate/optimizer/lookahead.py):
    every k steps, slow weights ← slow + alpha*(fast - slow); fast ←
    slow."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_num = 0
        self._slow = None

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        params = self.inner_optimizer._parameter_list
        if self._slow is None:
            self._slow = [jnp.array(p._value) for p in params]
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p._value -
                                                     self._slow[i])
                self._slow[i] = slow
                p._replace(slow)

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        if self._slow is not None:
            sd["slow_params"] = [np.asarray(s) for s in self._slow]
        sd["lookahead_step"] = self._step_num
        return sd


class ModelAverage:
    """Running average of parameters for eval (reference
    incubate/optimizer/modelaverage.py): apply()/restore() swap averaged
    weights in and out."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params: List = list(parameters or [])
        self._sum = None
        self._count = 0
        self._backup = None

    def step(self):
        if self._sum is None:
            self._sum = [jnp.array(p._value) for p in self._params]
            self._count = 1
        else:
            self._sum = [s + p._value
                         for s, p in zip(self._sum, self._params)]
            self._count += 1

    def apply(self, executor=None, need_restore: bool = True):
        if self._sum is None:
            return
        self._backup = [jnp.array(p._value) for p in self._params]
        for p, s in zip(self._params, self._sum):
            p._replace(s / self._count)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._replace(b)
        self._backup = None
