"""incubate.asp — 2:4 structured sparsity (Automatic SParsity).

Reference: /root/reference/python/paddle/incubate/asp/ (mask calculation
in utils.py: get_mask_1d/2d_greedy/best, prune_model, decorate). TPU
note: the MXU has no 2:4 sparse path, so pruning here is a numerics/
model-compression feature (masks enforced on weights + re-applied after
optimizer steps), matching the reference's semantics if not its GPU
speedup.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...framework.core import Parameter, Tensor

__all__ = ["calculate_density", "check_sparsity", "create_mask",
           "get_mask_1d", "get_mask_2d_greedy", "prune_model", "decorate",
           "reset_excluded_layers", "set_excluded_layers"]

_excluded: List[str] = []


def calculate_density(x) -> float:
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float((arr != 0).sum() / arr.size)


def get_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive weights (rows)."""
    flat = mat.reshape(-1, m)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat, dtype=bool)
    np.put_along_axis(mask, idx, True, axis=1)
    return mask.reshape(mat.shape)


def get_mask_2d_greedy(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """2D n:m over m x m blocks: greedily keep the largest-|w| entries
    subject to <= n survivors per block-row AND per block-column
    (reference utils.py get_mask_2d_greedy semantics). Requires both
    dims divisible by m (callers pad)."""
    h, w = mat.shape
    assert h % m == 0 and w % m == 0, "pad to multiples of m first"
    mask = np.zeros_like(mat, dtype=bool)
    absw = np.abs(mat)
    for bi in range(0, h, m):
        for bj in range(0, w, m):
            block = absw[bi:bi + m, bj:bj + m]
            order = np.argsort(-block, axis=None)
            rows_used = np.zeros(m, np.int64)
            cols_used = np.zeros(m, np.int64)
            for flat in order:
                r, c = divmod(int(flat), m)
                if rows_used[r] < n and cols_used[c] < n:
                    mask[bi + r, bj + c] = True
                    rows_used[r] += 1
                    cols_used[c] += 1
    return mask


def create_mask(tensor, func_name: str = "get_mask_1d", n: int = 2,
                m: int = 4):
    arr = np.asarray(tensor._value if isinstance(tensor, Tensor)
                     else tensor)
    shape = arr.shape
    flat = arr.reshape(shape[0], -1) if arr.ndim > 1 else arr.reshape(1, -1)
    pad_c = (-flat.shape[1]) % m
    pad_r = (-flat.shape[0]) % m if func_name == "get_mask_2d_greedy" else 0
    if pad_c or pad_r:
        flat = np.pad(flat, ((0, pad_r), (0, pad_c)))
    fn = {"get_mask_1d": get_mask_1d,
          "get_mask_2d_greedy": get_mask_2d_greedy}[func_name]
    mask = fn(flat, n, m)
    if pad_r:
        mask = mask[:-pad_r]
    if pad_c:
        mask = mask[:, :-pad_c]
    return mask.reshape(shape)


def check_sparsity(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    flat = np.asarray(mat).reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.pad(flat, (0, pad))
    groups = flat.reshape(-1, m)
    return bool(((groups != 0).sum(axis=1) <= n).all())


def set_excluded_layers(param_names: List[str], main_program=None):
    _excluded.extend(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


_masks: Dict[int, np.ndarray] = {}


def _prunable(name: str, p: Parameter) -> bool:
    if any(ex in name for ex in _excluded):
        return False
    return p.ndim >= 2 and p.shape[-1] % 4 == 0


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every prunable parameter of a Layer."""
    algo = {"mask_1d": "get_mask_1d",
            "mask_2d_greedy": "get_mask_2d_greedy"}.get(mask_algo,
                                                        "get_mask_1d")
    import jax.numpy as jnp
    pruned = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        mask = create_mask(p, algo, n, m)
        p._replace(p._value * jnp.asarray(mask, p._value.dtype))
        _masks[id(p)] = mask
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update
    (reference ASPHelper.decorate → OptimizerWithSparsityGuarantee)."""
    import jax.numpy as jnp
    orig_step = optimizer.step

    def step():
        orig_step()
        for p in optimizer._parameter_list:
            mask = _masks.get(id(p))
            if mask is not None:
                p._replace(p._value * jnp.asarray(mask, p._value.dtype))
    optimizer.step = step
    return optimizer
