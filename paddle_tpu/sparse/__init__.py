"""paddle_tpu.sparse — sparse tensors over jax.experimental.sparse.

Reference: /root/reference/python/paddle/sparse/ (SparseCooTensor /
SparseCsrTensor C++ types, creation.py, unary/binary/matmul ops,
sparse.nn). TPU-native: the storage is jax.experimental.sparse.BCOO
(COO) — XLA lowers scatter/gather/dot_general on it natively — wrapped
in a SparseTensor facade carrying the paddle API (indices/values/
to_dense/to_sparse_coo). CSR creation is accepted and represented
internally as BCOO (crows decompressed), keeping the API while letting
XLA pick layouts.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, to_tensor
from ..framework import dtype as dtypes

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseTensor",
    "is_same_shape", "add", "subtract", "multiply", "divide", "matmul",
    "masked_matmul", "relu", "sqrt", "sin", "tanh", "to_dense",
    "coalesce", "nn",
]


class SparseTensor:
    """COO sparse tensor facade over BCOO."""

    def __init__(self, bcoo: jsparse.BCOO, fmt: str = "coo",
                 crows=None, cols=None):
        self._bcoo = bcoo
        self._fmt = fmt
        self._crows = crows      # kept for csr round-trip
        self._cols = cols

    # -- properties ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return np.dtype(self._bcoo.dtype)

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def crows(self) -> Tensor:
        if self._crows is None:
            raise ValueError("not a CSR tensor")
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        if self._cols is None:
            raise ValueError("not a CSR tensor")
        return Tensor(self._cols)

    def is_sparse_coo(self) -> bool:
        return self._fmt == "coo"

    def is_sparse_csr(self) -> bool:
        return self._fmt == "csr"

    # -- conversion ---------------------------------------------------------
    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim: Optional[int] = None):
        return SparseTensor(self._bcoo, "coo")

    def to_sparse_csr(self):
        dense = np.asarray(self._bcoo.todense())
        return _dense_to_csr(dense)

    def coalesce(self):
        return SparseTensor(self._bcoo.sum_duplicates(), self._fmt,
                            self._crows, self._cols)

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseTensor(fmt={self._fmt}, shape={self.shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseTensor:
    """paddle.sparse.sparse_coo_tensor parity (creation.py). indices:
    [ndim, nnz]."""
    idx = np.asarray(indices._value if isinstance(indices, Tensor)
                     else indices)
    val = jnp.asarray(values._value if isinstance(values, Tensor)
                      else values,
                      dtype=dtypes.convert_dtype(dtype) if dtype else None)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    bcoo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseTensor(bcoo, "coo")


def _dense_to_csr(dense: np.ndarray) -> SparseTensor:
    assert dense.ndim == 2, "CSR requires 2-D"
    rows, cols = np.nonzero(dense)
    vals = dense[rows, cols]
    crows = np.zeros(dense.shape[0] + 1, np.int64)
    for r in rows:
        crows[r + 1] += 1
    crows = np.cumsum(crows)
    bcoo = jsparse.BCOO((jnp.asarray(vals),
                         jnp.asarray(np.stack([rows, cols], 1))),
                        shape=dense.shape)
    return SparseTensor(bcoo, "csr", jnp.asarray(crows),
                        jnp.asarray(cols))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseTensor:
    """CSR creation (stored as BCOO internally; crows kept for API)."""
    cr = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cl = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    val = jnp.asarray(values._value if isinstance(values, Tensor)
                      else values,
                      dtype=dtypes.convert_dtype(dtype) if dtype else None)
    rows = np.repeat(np.arange(len(cr) - 1), np.diff(cr))
    bcoo = jsparse.BCOO((val, jnp.asarray(np.stack([rows, cl], 1))),
                        shape=tuple(shape))
    return SparseTensor(bcoo, "csr", jnp.asarray(cr), jnp.asarray(cl))


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _as_bcoo(x):
    if isinstance(x, SparseTensor):
        return x._bcoo
    raise TypeError(f"expected SparseTensor, got {type(x)}")


def add(x: SparseTensor, y) -> SparseTensor:
    if isinstance(y, SparseTensor):
        out = x._bcoo + y._bcoo
        return SparseTensor(out.sum_duplicates(), "coo")
    dense = x._bcoo.todense() + (y._value if isinstance(y, Tensor)
                                 else jnp.asarray(y))
    return SparseTensor(jsparse.BCOO.fromdense(dense), "coo")


def subtract(x: SparseTensor, y: SparseTensor) -> SparseTensor:
    neg = jsparse.BCOO((-y._bcoo.data, y._bcoo.indices),
                       shape=y._bcoo.shape)
    return SparseTensor((x._bcoo + neg).sum_duplicates(), "coo")


def multiply(x: SparseTensor, y) -> SparseTensor:
    if isinstance(y, SparseTensor):
        dense = x._bcoo.todense() * y._bcoo.todense()
        return SparseTensor(jsparse.BCOO.fromdense(dense), "coo")
    scalar = y._value if isinstance(y, Tensor) else y
    return SparseTensor(
        jsparse.BCOO((x._bcoo.data * scalar, x._bcoo.indices),
                     shape=x._bcoo.shape), x._fmt, x._crows, x._cols)


def divide(x: SparseTensor, y) -> SparseTensor:
    scalar = y._value if isinstance(y, Tensor) else y
    return SparseTensor(
        jsparse.BCOO((x._bcoo.data / scalar, x._bcoo.indices),
                     shape=x._bcoo.shape), x._fmt, x._crows, x._cols)


def matmul(x: SparseTensor, y) -> Tensor:
    """sparse @ dense → dense (XLA lowers BCOO dot_general natively)."""
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(x._bcoo @ yv)


def masked_matmul(x: Tensor, y: Tensor, mask: SparseTensor) -> SparseTensor:
    """dense @ dense sampled at mask's sparsity (SDDMM)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape),
                        "coo")


def _unary(name, f):
    def op(x: SparseTensor) -> SparseTensor:
        return SparseTensor(
            jsparse.BCOO((f(x._bcoo.data), x._bcoo.indices),
                         shape=x._bcoo.shape), x._fmt, x._crows, x._cols)
    op.__name__ = name
    return op


relu = _unary("relu", lambda d: jnp.maximum(d, 0))
sqrt = _unary("sqrt", jnp.sqrt)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)


def to_dense(x: SparseTensor) -> Tensor:
    return x.to_dense()


def coalesce(x: SparseTensor) -> SparseTensor:
    return x.coalesce()


class _SparseNN:
    """sparse.nn namespace: ReLU layer parity (sparse/nn/layer/
    activation.py)."""

    class ReLU:
        def __call__(self, x: SparseTensor) -> SparseTensor:
            return relu(x)

        def __repr__(self):
            return "sparse.nn.ReLU()"


nn = _SparseNN()


# elementwise unary parity (reference sparse/unary.py — value-wise maps
# that keep the sparsity pattern; sum/transpose/reshape/slice/... are
# structural)
abs = _unary("abs", jnp.abs)          # noqa: A001
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
deg2rad = _unary("deg2rad", jnp.deg2rad)
expm1 = _unary("expm1", jnp.expm1)
isnan = _unary("isnan", jnp.isnan)
log1p = _unary("log1p", jnp.log1p)
neg = _unary("neg", jnp.negative)
rad2deg = _unary("rad2deg", jnp.rad2deg)
sinh = _unary("sinh", jnp.sinh)
square = _unary("square", jnp.square)
tan = _unary("tan", jnp.tan)


def cast(x: SparseTensor, index_dtype=None, value_dtype=None, name=None):
    b = x._bcoo
    data = b.data if value_dtype is None else \
        b.data.astype(dtypes.convert_dtype(value_dtype))
    idx = b.indices if index_dtype is None else \
        b.indices.astype(dtypes.convert_dtype(index_dtype))
    return SparseTensor(jsparse.BCOO((data, idx), shape=b.shape))


def pow(x: SparseTensor, factor, name=None):    # noqa: A001
    return _unary("pow", lambda d: jnp.power(d, factor))(x)


def sum(x: SparseTensor, axis=None, dtype=None, keepdim=False,    # noqa: A001
        name=None):
    d = x.to_dense()._value
    out = jnp.sum(d if dtype is None
                  else d.astype(dtypes.convert_dtype(dtype)),
                  axis=axis, keepdims=keepdim)
    return Tensor(out)


def transpose(x: SparseTensor, perm, name=None):
    dense = jnp.transpose(x.to_dense()._value, perm)
    return SparseTensor(jsparse.BCOO.fromdense(dense))


def reshape(x: SparseTensor, shape, name=None):
    dense = jnp.reshape(x.to_dense()._value, shape)
    return SparseTensor(jsparse.BCOO.fromdense(dense))


def slice(x: SparseTensor, axes, starts, ends, name=None):    # noqa: A001
    import builtins
    d = x.to_dense()._value
    sl = [builtins.slice(None)] * d.ndim
    for ax, s0, e0 in zip(axes, starts, ends):
        sl[ax] = builtins.slice(int(s0), int(e0))
    return SparseTensor(jsparse.BCOO.fromdense(d[tuple(sl)]))


def mv(x: SparseTensor, vec, name=None):
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(x._bcoo @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) where x may be sparse (reference
    sparse.addmm)."""
    xv = x._bcoo if isinstance(x, SparseTensor) else (
        x._value if isinstance(x, Tensor) else jnp.asarray(x))
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    iv = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(beta * iv + alpha * (xv @ yv))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..tensor import pca_lowrank as _dense_pca
    d = x.to_dense() if isinstance(x, SparseTensor) else x
    return _dense_pca(d, q=q, center=center, niter=niter)


__all__ += ["abs", "asin", "asinh", "atan", "atanh", "deg2rad", "expm1",
            "isnan", "log1p", "neg", "rad2deg", "sinh", "square", "tan",
            "cast", "pow", "sum", "transpose", "reshape", "slice", "mv",
            "addmm", "pca_lowrank"]
