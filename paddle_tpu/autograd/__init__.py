"""paddle.autograd parity: functional grad, PyLayer custom-op autograd.

The reference implements these in C++ (/root/reference/paddle/fluid/eager/
backward.cc:439 `Grad`, pylayer op). Here both ride the same Python tape over
jax.vjp closures.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import (
    Tensor, TapeNode, no_grad, is_grad_enabled, _run_backward,
)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        t.backward(g, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None) -> List[Optional[Tensor]]:
    """paddle.grad analog (reference: paddle/fluid/eager/backward.cc:439).
    create_graph (higher-order) is not supported on the eager tape — use
    jax.grad composition through paddle_tpu.jit for that."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: compose jax.grad via paddle_tpu.jit instead")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    retain = True if retain_graph is None else retain_graph

    # Collect into a side table: paddle.grad must not touch .grad of ANY
    # leaf (inputs or otherwise).
    saved_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    collected = {}

    def collector(t, g):
        prev = collected.get(id(t))
        collected[id(t)] = g if prev is None else prev + g

    try:
        if grad_outputs is None:
            grad_outputs = [None] * len(outputs)
        for o, go in zip(outputs, grad_outputs):
            if o.size != 1 and go is None:
                raise RuntimeError("grad_outputs required for non-scalar")
            seed = (go._value if isinstance(go, Tensor) else go)
            if seed is None:
                seed = jnp.ones(tuple(o.shape), o._value.dtype)
            from ..framework.core import _run_backward
            _run_backward(o, seed, retain, accum_fn=collector)
        results = []
        for t in inputs:
            g = collected.get(id(t))
            if g is None and not allow_unused:
                raise RuntimeError(
                    f"input {t.name or t} unused in the graph "
                    "(pass allow_unused=True to get None)")
            results.append(None if g is None else Tensor(g))
        return results
    finally:
        for t, old_sg in zip(inputs, saved_sg):
            t.stop_gradient = old_sg


class PyLayerContext:
    """ctx object passed to PyLayer.forward/backward
    (paddle.autograd.PyLayer parity)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom autograd op: subclass with static forward(ctx, ...) and
    backward(ctx, *grads). Mirrors paddle.autograd.PyLayer — the mechanism
    behind the reference's TP comm prims
    (/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py:27).
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        outs_list = list(outputs) if multi else [outputs]
        results = [o if isinstance(o, Tensor) else Tensor(o) for o in outs_list]

        if need_grad:
            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ct_tensors = [Tensor(c) for c in cts]
                with no_grad():
                    gs = cls.backward(ctx, *ct_tensors)
                gs = gs if isinstance(gs, (tuple, list)) else (gs,)
                out = []
                gi = iter(gs)
                for t in tensor_args:
                    g = next(gi, None)
                    out.append(None if g is None else
                               (g._value if isinstance(g, Tensor) else jnp.asarray(g)))
                return tuple(out)

            node = TapeNode(
                vjp_fn, tensor_args,
                [jax.ShapeDtypeStruct(tuple(r.shape), r.dtype) for r in results],
                cls.__name__)
            for k, r in enumerate(results):
                r._node = node
                r._out_idx = k
                r.stop_gradient = False

        if multi:
            return tuple(results)
        return results[0]
