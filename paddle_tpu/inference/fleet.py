"""Fleet-grade serving: a dp x tp replica mesh behind a health-checked
Router (ISSUE 11 — ROADMAP item 1).

One ServingEngine is one failure domain: PR 4 made it absorb every
fault at REQUEST granularity, but nothing above it could notice a whole
replica wedging — a flaky interconnect, a poisoned device, a runaway
compile. The Router owns R independent replicas (each a full
ServingEngine, tp-sharded over a DISJOINT device slice — row r of the
SpecLayout dp x tp grid) and adds the replica-level half of the story:

- prefix-affinity load balancing: each admission consults every
  eligible replica's PR-1 chain-hash index (PagedKVCache.match_prefix —
  a pure host-side hash walk, no device traffic) and routes to the
  replica whose cached coverage of the prompt is LONGEST; ties (and the
  no-coverage common case) break by least-loaded-then-lowest-index, so
  routing is deterministic. A saturated winner (EngineOverloaded from
  its queue cap or deadline math) SPILLS to the next candidate; when
  every replica refuses, the fleet sheds — the PR-4 machinery, one
  level up.
- per-replica health tracking with a circuit breaker: after every
  replica step the Router reads three signals — new _device_call retry
  EXHAUSTIONS (the engine's dispatch_exhaustions counter), a step
  wall-clock past stall_timeout_s (the watchdog-stall signal,
  synchronous form), and a step() exception (defensive; step() never
  raises by contract). Every retry exhaustion is one strike (a stall
  or exception floors at one); a clean step WITH device activity
  resets the count — consecutive semantics, so transient faults that
  the engine's own bounded retry absorbs never accumulate, while an
  idle step proves nothing in either direction; at breaker_threshold
  accumulated strikes the replica is WEDGED.
- drain-and-migrate failover: a wedged replica's live requests (and
  the requests its fault burst just failed) are harvested — prompt,
  sampling, generated history — cancelled locally (host-side unwind
  only; the wedged device is never touched), and re-enqueued on
  healthy replicas through ServingEngine.adopt_request: the history
  re-prefills via the PR-4 all-mid-chunk NO-SAMPLE path (zero PRNG
  keys drawn) and decode resumes from the last generated token, so
  greedy outputs are TOKEN-IDENTICAL across the migration (the chaos
  --dp leg gates this against a fault-free replay).
- optional probation: cooldown_steps after wedging, the replica
  re-enters routing on PROBATION — one strike re-wedges it instantly;
  probation_steps consecutive clean steps promote it back to healthy.
- replica transports (ISSUE 19): every engine access goes through a
  ReplicaTransport (inference/transport.py). ``transport="inproc"``
  (default) is the in-process engine, bitwise-identical to PR 11;
  ``transport="process"`` runs each engine in a SPAWNED worker process
  behind an RPC pipe — two extra health signals (missed heartbeat,
  process exit) feed the same breaker, and because a dead worker's
  memory is gone the Router keeps an authoritative per-request JOURNAL
  (prompt, sampling, delivered-token watermark, trace id) updated at
  collection with exactly-once semantics: failover reconstructs every
  in-flight request host-side and re-enqueues it via adopt_request —
  greedy outputs token-identical across a hard SIGKILL. A supervisor
  respawns dead workers (fresh engine, replayed warmup + seal, then
  the PR-11 probation re-admission).

dp adds ZERO step-path collectives: replicas never talk during a step
(affinity is a host-side hash lookup, migration is a host-side
re-enqueue), and every replica's step program is byte-for-byte the
single-engine tp program — pinned by the comm-audit entry
serving.ragged_dp2_tp2, whose expectations equal
serving.ragged_tp2_fp32's exactly.

Usage::

    from paddle_tpu.inference.fleet import Router
    router = Router(model, dp=2, tp=2, max_batch_size=8)
    fid = router.add_request(prompt_ids, SamplingParams(...))
    while router.step():
        pass
    tokens = router.result(fid)

Token-identity contract: GREEDY requests produce identical tokens no
matter which replica serves them and across any number of migrations
(every replica holds the same weights and migration re-prefills
without sampling). Stochastic requests stay request-granular-correct
but are NOT bit-reproducible across replicas — each engine owns an
independent PRNG stream, exactly like preemption's contract in PR 4.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.spec_layout import SpecLayout
from ..utils.telemetry import FLEET_PID, Reservoir, SLOMonitor, SLOPolicy
from .serving import (EngineOverloaded, SamplingParams, ServingEngine,
                      _normalize_prompt)
from .transport import (InProcTransport, ProcTransport, RequestView,
                        TransportError, WorkerDied, WorkerSpec)

__all__ = ["Router", "Replica"]

# journal states considered live (the engine's non-terminal states);
# terminal entries stop being acked and are pruned by clear_finished
_LIVE_STATES = ("queued", "prefilling", "running")


@dataclass
class Replica:
    """One engine plus its health record (Router-internal, exposed via
    ``router.replicas`` for tests/telemetry)."""
    idx: int
    engine: ServingEngine
    state: str = "healthy"          # healthy | probation | wedged
    strikes: int = 0                # consecutive faulty steps
    wedges: int = 0                 # times this replica tripped
    wedged_at: Optional[int] = None  # router step of the last wedge
    probation_clean: int = 0        # clean steps since probation began
    # engine.dispatch_exhaustions watermark (delta per step = faults)
    exh_mark: int = 0
    # engine.device_dispatches watermark: a step with NO device
    # activity is evidence of nothing — it neither strikes nor resets
    disp_mark: int = 0
    # engine req_ids already failed BEFORE the current strike burst:
    # at drain time only requests failed DURING the burst migrate (a
    # request that failed long ago was already observed as failed by
    # the caller — resurrecting it would change a delivered answer).
    # Rebuilt lazily: valid while engine.failed == snap_failed_cnt,
    # so the steady state (no failures) never rescans _done.
    # Remote replicas store journal FIDs here instead of engine rids
    # (the journal is the Router's only authoritative view of a
    # worker it cannot trust to answer)
    burst_failed_mark: frozenset = frozenset()
    snap_failed_cnt: int = 0
    # the transport driving this replica (ISSUE 19): InProcTransport
    # wraps `engine` (kept live for tests/harnesses); ProcTransport
    # owns a worker process and `engine` is None
    transport: object = None
    # last step's worker-reported load (remote replicas: the counter
    # track must not cost an RPC; in-proc replicas read the engine)
    last_load: int = 0


@dataclass
class _JournalEntry:
    """Router-side delivery journal for ONE fleet request (ISSUE 19):
    the delivered-token watermark plus last observed state. Together
    with _FleetRequest (prompt, sampling, trace_id) this is everything
    failover needs to reconstruct an in-flight request after a worker
    dies with its memory. ``delivered`` only ever EXTENDS past its
    current length against the reply's ack base — exactly-once no
    matter how many times a step reply crosses the pipe."""
    fid: int
    state: str = "queued"
    delivered: List[int] = None     # type: ignore[assignment]
    error: Optional[str] = None

    def __post_init__(self):
        if self.delivered is None:
            self.delivered = []


@dataclass
class _FleetRequest:
    """Fleet-level request record: which replica currently owns it."""
    fid: int
    prompt: np.ndarray
    sampling: SamplingParams
    replica: int
    rid: int                        # engine-local req_id on `replica`
    t_submit: float = 0.0
    migrations: int = 0
    # telemetry span id (ISSUE 12): opened by the owning engine's
    # add_request, carried through adopt_request at migration so the
    # whole lifecycle is ONE continuous span across replicas
    trace_id: Optional[int] = None


class Router:
    """R ServingEngine replicas behind prefix-affinity routing, health
    tracking with a circuit breaker, and drain-and-migrate failover.

    Parameters
    ----------
    model : the LlamaForCausalLM (or GPT) every replica serves. Ignored
        when ``engine_factory`` is given.
    dp : replica count R.
    tp : per-replica tensor-parallel degree; tp > 1 places replica r on
        row r of ``SpecLayout.fleet_device_slices(dp, tp)`` — disjoint
        device slices, dp x tp chips total.
    affinity : route by longest cached chain-hash coverage (True, the
        default) or purely by load (False — the bench A/B's off leg).
    breaker_threshold : consecutive faulty steps before a replica is
        declared wedged and drained.
    stall_timeout_s : a single engine step taking longer than this
        counts as a watchdog-stall strike (None disables — CPU test
        meshes stall for compile reasons, not health reasons).
    cooldown_steps : router steps after a wedge before the replica
        re-enters routing on probation (None = stay wedged forever).
    probation_steps : consecutive clean steps that promote a probation
        replica back to healthy.
    engine_factory : optional ``f(replica_idx, devices) ->
        ServingEngine`` overriding default construction — prebuilt
        decoders, GPT twins, per-replica AdapterRegistry instances (a
        registry binds to one engine's pool and must NOT be shared
        across replicas). With ``transport="process"`` the factory is
        pickled to the worker, so it must be a module-level callable.
    transport : ``"inproc"`` (default — engines live in this process,
        bitwise-identical to the pre-transport Router) or
        ``"process"`` — each engine in a SPAWNED worker behind an RPC
        pipe (crash isolation; see inference/transport.py).
    heartbeat_timeout_s : (process transport) heartbeat silence beyond
        this is a breaker strike per step — the liveness signal for a
        hung-but-not-dead worker. None disables.
    rpc_timeout_s / rpc_retries : (process transport) per-RPC deadline
        and bounded retry budget for transient transport faults
        (exactly-once by the worker's reply cache).
    respawn : (process transport) supervisor restart of dead workers —
        fresh engine, replayed warmup/seal, probation re-admission.
    **engine_kwargs : forwarded to every ServingEngine (max_batch_size,
        num_blocks, prefill_chunk, ragged, spec_decode, ...).
    """

    def __init__(self, model, dp: int = 2, tp: int = 1, *,
                 affinity: bool = True,
                 breaker_threshold: int = 3,
                 stall_timeout_s: Optional[float] = None,
                 cooldown_steps: Optional[int] = None,
                 probation_steps: int = 8,
                 engine_factory: Optional[Callable] = None,
                 transport: str = "inproc",
                 heartbeat_timeout_s: Optional[float] = 10.0,
                 rpc_timeout_s: float = 120.0,
                 rpc_retries: int = 2,
                 respawn: bool = True,
                 tracer=None, slo=None,
                 **engine_kwargs):
        dp = int(dp)
        if dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        if transport not in ("inproc", "process"):
            raise ValueError(f"transport must be 'inproc' or "
                             f"'process', got {transport!r}")
        self.dp = dp
        self.tp = int(tp)
        self.affinity = bool(affinity)
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.stall_timeout_s = stall_timeout_s
        self.cooldown_steps = (int(cooldown_steps)
                               if cooldown_steps is not None else None)
        self.probation_steps = max(1, int(probation_steps))
        self.transport = transport
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.rpc_retries = int(rpc_retries)
        self.respawn = bool(respawn)
        # per-replica device rows from the canonical dp x tp grid
        # (tp=1 replicas share the default device — placement only
        # matters once a replica actually spans chips)
        layout = SpecLayout()
        slices = (layout.fleet_device_slices(dp, tp) if self.tp > 1
                  else [None] * dp)
        # telemetry (ISSUE 12): ONE shared Tracer across the Router and
        # every replica engine — per-request spans carry replica pids
        # and routing/breaker/migration events land on the fleet track,
        # so a migrated request renders as a single continuous span
        # crossing two replica tracks. tracer=None is a bitwise no-op.
        self.tracer = tracer
        # SLO monitoring (ISSUE 14): `slo` is a sequence of SLOPolicy
        # declarations (or one policy / an SLOMonitor whose policies
        # are taken as the template). Each replica gets its OWN
        # monitor over the shared policy set — windows must be
        # per-replica or one slow replica's tail hides inside the
        # fleet aggregate; stats() rolls the per-replica headrooms up
        # (the SLO-aware-routing input, ROADMAP 1)
        self._slo_policies: List[SLOPolicy] = \
            SLOMonitor.coerce_policies(slo)
        if self._slo_policies and engine_factory is not None:
            # a factory builds its engines itself — Router-level
            # policies would be silently ignored; fail loudly instead
            raise ValueError("pass slo= to the Router only without "
                             "engine_factory (give factory-built "
                             "engines their own SLOMonitor)")
        self.replicas: List[Replica] = []
        for r in range(dp):
            if transport == "process":
                spec = WorkerSpec(
                    model=(None if engine_factory is not None
                           else model),
                    factory=engine_factory, dp=dp, tp=self.tp,
                    engine_kwargs=dict(engine_kwargs),
                    slo_policies=tuple(self._slo_policies),
                    traced=tracer is not None)
                tr = ProcTransport(
                    spec, replica_id=r, tracer=tracer,
                    rpc_timeout_s=self.rpc_timeout_s,
                    rpc_retries=self.rpc_retries)
                self.replicas.append(Replica(r, None, transport=tr))
                continue
            if engine_factory is not None:
                eng = engine_factory(r, slices[r])
            else:
                kw = dict(engine_kwargs)
                if self._slo_policies:
                    kw["slo"] = SLOMonitor(self._slo_policies)
                eng = ServingEngine(model, tp=tp, devices=slices[r],
                                    **kw)
            if tracer is not None:
                eng.set_telemetry(tracer, replica_id=r)
            self.replicas.append(Replica(r, eng,
                                         transport=InProcTransport(
                                             eng)))
        self._requests: Dict[int, _FleetRequest] = {}
        # per-request delivery journal (ISSUE 19): authoritative for
        # BOTH transports (uniform gauges + acks); only the process
        # transport depends on it for correctness
        self._journal: Dict[int, _JournalEntry] = {}
        self._fids = itertools.count()
        self._step_no = 0
        self._closed = False
        # routing / robustness counters (reset by clear_finished)
        self.routed_requests = 0
        self.affinity_hits = 0
        self.spills = 0
        self.failovers = 0
        self.migrated_requests = 0
        self.failed_migrations = 0
        self.shed_requests = 0
        # fleet-process counters (ISSUE 19, reset by clear_finished)
        self.worker_exits = 0
        self.worker_restarts = 0
        self.heartbeat_misses = 0

    # -- routing policy ------------------------------------------------------
    def _eligible(self) -> List[Replica]:
        return [rep for rep in self.replicas if rep.state != "wedged"]

    @staticmethod
    def _load(eng: ServingEngine) -> int:
        """Host-side load proxy: live requests (queued + slotted)."""
        return len(eng._queue) + sum(1 for s in eng._slots
                                     if s is not None)

    @staticmethod
    def _coverage(eng: ServingEngine, prompt, salt) -> int:
        """Cached chain-hash coverage of `prompt` on this replica, in
        tokens — the PR-1 index walk, pure host-side."""
        if not eng.prefix_caching:
            return 0
        cache = eng.dec.cache
        return len(cache.match_prefix(prompt, salt)) * cache.block_size

    def _cov_of(self, rep: Replica, prompt, salt) -> int:
        """Transport coverage probe, fault-tolerant: a dying remote
        replica answers 0 (it will be wedged by the next step; routing
        must not crash on it)."""
        try:
            return rep.transport.match_coverage(prompt, salt)
        except TransportError:
            return 0

    def _load_of(self, rep: Replica) -> int:
        try:
            return rep.transport.load()
        except TransportError:
            return 1 << 30      # dying remote: route anywhere else

    def _ranked(self, prompt, sp: SamplingParams,
                exclude: Sequence[int] = ()
                ) -> Tuple[List[Replica], Dict[int, int]]:
        """Admission order: longest coverage first (affinity), ties —
        and the affinity=False mode — by (load, replica idx). Fully
        deterministic: equal fleets route equal traffic equally (the
        process transport's coverage/load probes are exact RPCs, so an
        inproc fleet and a process fleet route identically)."""
        cands = [rep for rep in self._eligible()
                 if rep.idx not in exclude]
        cov = {rep.idx: (self._cov_of(rep, prompt, sp.adapter_id)
                         if self.affinity else 0)
               for rep in cands}
        return sorted(cands, key=lambda rep: (-cov[rep.idx],
                                              self._load_of(rep),
                                              rep.idx)), cov

    def add_request(self, prompt, sampling: Optional[SamplingParams]
                    = None) -> int:
        """Route one admission through the fleet. Returns a FLEET
        request id (stable across migrations). Raises EngineOverloaded
        only when EVERY eligible replica sheds it (per-replica queue
        caps and deadline estimates are the PR-4 machinery, consulted
        replica by replica — a saturated affinity winner spills to the
        next candidate instead of shedding)."""
        sp = sampling or SamplingParams()
        prompt = _normalize_prompt(prompt)
        order, cov = self._ranked(prompt, sp)
        if not order:
            self.shed_requests += 1
            if self.tracer is not None:
                self.tracer.event("fleet_shed", pid=FLEET_PID,
                                  reason="all_wedged")
            raise EngineOverloaded("fleet has no eligible replica "
                                   "(all wedged)")
        last_exc = invalid = None
        for pos, rep in enumerate(order):
            try:
                rid, tid = rep.transport.add_request(prompt, sp)
            except EngineOverloaded as e:
                last_exc = e
                continue
            except (KeyError, ValueError) as e:
                # per-replica validation refusal (engine_factory fleets
                # may be heterogeneous: an adapter registered on only
                # some replicas, differing pool geometry) — try the
                # next candidate; if EVERY replica refuses this way the
                # request is genuinely invalid and the first error is
                # the honest one to surface
                invalid = invalid or e
                continue
            except TransportError as e:
                # a dying remote replica refuses like a saturated one:
                # spill to the next candidate (the breaker wedges it
                # on its own evidence at the next step)
                last_exc = e
                continue
            fid = next(self._fids)
            rec = _FleetRequest(fid, prompt, sp, rep.idx, rid,
                                t_submit=time.perf_counter())
            rec.trace_id = tid
            self._requests[fid] = rec
            self._journal[fid] = _JournalEntry(fid)
            self.routed_requests += 1
            if cov.get(rep.idx, 0) > 0:
                self.affinity_hits += 1
            if pos > 0:
                self.spills += 1
            if self.tracer is not None:
                self.tracer.event(
                    "route", trace=rec.trace_id, pid=FLEET_PID,
                    fid=fid, replica=rep.idx,
                    coverage=int(cov.get(rep.idx, 0)), spill=pos)
            return fid
        if invalid is not None and last_exc is None:
            raise invalid          # rejected everywhere: caller error
        self.shed_requests += 1
        if self.tracer is not None:
            self.tracer.event("fleet_shed", pid=FLEET_PID,
                              reason="saturated")
        raise EngineOverloaded(
            f"fleet saturated: all {len(order)} eligible replica(s) "
            f"shed the request (last: {last_exc or invalid})")

    # -- request surface -----------------------------------------------------
    def _record(self, fid: int) -> _FleetRequest:
        rec = self._requests.get(fid)
        if rec is None:
            raise KeyError(f"unknown fleet request {fid}")
        return rec

    def _owner(self, fid: int) -> Replica:
        return self.replicas[self._record(fid).replica]

    def _journal_view(self, fid: int) -> Optional[RequestView]:
        """Reconstruct a request view from the journal — the fallback
        when the owning WORKER's memory is gone (died, or respawned
        fresh). Exact for terminal requests: the terminal delivery
        carried every remaining token before the state flipped."""
        rec = self._requests.get(fid)
        je = self._journal.get(fid)
        if rec is None or je is None:
            return None
        return RequestView(req_id=rec.rid, state=je.state,
                           out_tokens=list(je.delivered),
                           error=je.error, trace_id=rec.trace_id)

    def request(self, fid: int):
        """The current owner's Request record (live or terminal). For
        a process-transport replica this is a RequestView (same duck
        type); if the owning worker died or was respawned, the view
        is reconstructed from the Router's journal."""
        rec = self._record(fid)
        rep = self.replicas[rec.replica]
        if not rep.transport.remote:
            req = rep.engine._find_request(rec.rid)
            if req is None:
                raise KeyError(f"fleet request {fid}: engine record "
                               f"{rec.rid} gone (cleared?)")
            return req
        # TERMINAL entries answer from the journal, never the worker:
        # the terminal delivery carried every remaining token, and a
        # RESPAWNED worker's fresh engine restarts its req_id counter
        # at 0 — the stale rec.rid may now name a DIFFERENT request
        je = self._journal.get(fid)
        if je is not None and je.state not in _LIVE_STATES:
            return self._journal_view(fid)
        try:
            view = rep.transport.view(rec.rid)
        except TransportError:
            view = None
        if view is not None:
            return view
        view = self._journal_view(fid)
        if view is not None:
            return view
        raise KeyError(f"fleet request {fid}: engine record "
                       f"{rec.rid} gone (cleared?)")

    def result(self, fid: int) -> np.ndarray:
        rec = self._record(fid)
        rep = self.replicas[rec.replica]
        if not rep.transport.remote:
            return rep.engine.result(rec.rid)
        # journal-first for terminal states: the delivered watermark
        # IS the full output, and it cannot alias a respawned
        # worker's recycled req_id the way rec.rid can
        je = self._journal.get(fid)
        if je is not None and je.state not in _LIVE_STATES:
            return np.asarray(je.delivered, np.int32)
        try:
            return rep.transport.result(rec.rid)
        except (KeyError, TransportError):
            raise KeyError(f"fleet request {fid}: result not "
                           f"available (rid {rec.rid})")

    def migrations(self, fid: int) -> int:
        return self._record(fid).migrations

    def cancel(self, fid: int) -> bool:
        rec = self._record(fid)
        rep = self.replicas[rec.replica]
        if rep.transport.remote:
            je = self._journal.get(fid)
            if je is not None and je.state not in _LIVE_STATES:
                # terminal per the journal: the inproc False-on-
                # terminal contract, without risking a stale rec.rid
                # cancelling a respawned worker's recycled req_id
                return False
        return rep.transport.cancel(rec.rid)

    @property
    def has_work(self) -> bool:
        """Work remains on some replica the Router will still step —
        non-wedged ones always; wedged ones only if probation can
        revive them (their live queue was drained at wedge time, so
        this is almost always the non-wedged term)."""
        return any(rep.transport.has_work() for rep in self.replicas
                   if rep.state != "wedged"
                   or self.cooldown_steps is not None)

    # -- health / failover ---------------------------------------------------
    def _failed_rids(self, eng: ServingEngine) -> frozenset:
        return frozenset(rid for rid, r in eng._done.items()
                         if r.state == "failed")

    def _failed_fids(self, rep: Replica) -> frozenset:
        """Journal fids already failed on `rep` — the remote replica's
        burst snapshot (its engine cannot be trusted to answer when
        the burst is a dying process)."""
        return frozenset(
            fid for fid, je in self._journal.items()
            if je.state == "failed"
            and (rec := self._requests.get(fid)) is not None
            and rec.replica == rep.idx)

    def _acks_for(self, rep: Replica
                  ) -> Tuple[Dict[int, int], Dict[int, int]]:
        """(acks, rid->fid) for one replica's step RPC: every live
        journal entry it owns, acked at its delivered watermark."""
        acks: Dict[int, int] = {}
        ridmap: Dict[int, int] = {}
        for fid, je in self._journal.items():
            if je.state not in _LIVE_STATES:
                continue
            rec = self._requests.get(fid)
            if rec is None or rec.replica != rep.idx:
                continue
            acks[rec.rid] = len(je.delivered)
            ridmap[rec.rid] = fid
        return acks, ridmap

    def _apply_deliveries(self, deliveries, ridmap: Dict[int, int]):
        """Extend the journal exactly once per token: a delivery's
        tokens start at its echoed ack base, so extension happens only
        past the CURRENT watermark — idempotent under RPC retry (the
        same reply applied twice extends nothing the second time)."""
        for d in deliveries:
            fid = ridmap.get(d["rid"])
            if fid is None:
                continue
            je = self._journal.get(fid)
            if je is None:
                continue
            have = len(je.delivered)
            base = d["base"]
            toks = d["tokens"]
            if base <= have < base + len(toks):
                je.delivered.extend(toks[have - base:])
            if d["state"] != "gone":
                je.state = d["state"]
                je.error = d["error"]

    def _strike(self, rep: Replica, amount: int,
                prestep_mark: frozenset):
        """Accumulate fault evidence. `amount` is the step's retry-
        exhaustion count (each exhaustion is one consecutive
        _device_call failure — a step that exhausts three dispatches is
        three strikes, not one), floored at 1 for stall/exception
        strikes. Strikes reset only on a CLEAN step with device
        activity, so a replica that killed its whole queue and went
        idle keeps its evidence until the breaker decides."""
        if rep.strikes == 0:
            # burst starts: the PRE-step snapshot of what had already
            # failed — taken before this step's own casualties, so the
            # drain migrates everything THIS burst killed and nothing
            # a caller already observed as failed
            rep.burst_failed_mark = prestep_mark
        rep.strikes += max(1, int(amount))
        if self.tracer is not None:
            self.tracer.event("breaker_strike", pid=FLEET_PID,
                              replica=rep.idx, strikes=rep.strikes,
                              amount=int(amount), state=rep.state)
        limit = 1 if rep.state == "probation" else self.breaker_threshold
        if rep.strikes >= limit:
            self._wedge(rep)

    def _wedge(self, rep: Replica):
        rep.state = "wedged"
        rep.wedges += 1
        rep.wedged_at = self._step_no
        rep.strikes = 0
        self.failovers += 1
        if self.tracer is not None:
            self.tracer.event("breaker_wedge", pid=FLEET_PID,
                              replica=rep.idx, wedges=rep.wedges,
                              step=self._step_no)
        self._drain(rep)

    def _drain(self, rep: Replica):
        """Harvest every fleet request the wedged replica still owes an
        answer for — live (queued/prefilling/running) plus the ones its
        fault burst just failed — cancel them locally (host-side pool
        unwind only; nothing is dispatched to the wedged device) and
        re-enqueue them on healthy replicas as prompt+generated-history
        recomputes. Migration order is fid order: deterministic, FIFO-
        fair. With no healthy replica left the requests stay terminal
        on the wedged engine (the fleet is down; results of already-
        finished requests remain readable)."""
        if rep.transport.remote:
            self._drain_remote(rep)
            return
        eng = rep.engine
        victims = []            # (record, out_tokens harvested)
        for fid in sorted(self._requests):
            rec = self._requests[fid]
            if rec.replica != rep.idx:
                continue
            req = eng._find_request(rec.rid)
            if req is None:
                continue
            if req.state in ("queued", "prefilling", "running"):
                victims.append((rec, list(req.out_tokens)))
                # the local abort is a MIGRATION, not a terminal end:
                # keep the lifetime span open so the adopted
                # continuation on the new replica stays one span
                req.trace_keep_open = True
                try:
                    eng.cancel(rec.rid)
                except Exception:       # noqa: BLE001 — wedged engine:
                    pass                # best-effort local unwind
            elif (req.state == "failed"
                  and rec.rid not in rep.burst_failed_mark):
                victims.append((rec, list(req.out_tokens)))
                if self.tracer is not None:
                    # the burst failure already closed this span; the
                    # migration supersedes it — rescind the end so the
                    # adopted continuation keeps ONE continuous span
                    self.tracer.reopen_request(rec.trace_id)
        if self.tracer is not None:
            self.tracer.event("failover", pid=FLEET_PID,
                              replica=rep.idx, victims=len(victims))
        for rec, toks in victims:
            self._migrate(rec, toks)

    def _drain_remote(self, rep: Replica):
        """The journal-backed drain (ISSUE 19): a remote replica's
        memory may be GONE (SIGKILL) or unreachable (hang), so the
        harvest reads the Router's own journal — delivered-token
        watermarks updated at collection with exactly-once semantics —
        instead of the engine. Live entries migrate with their
        delivered history (token-identical greedy resume); entries the
        fault burst failed migrate like the in-proc path. Cancels are
        best-effort RPCs, skipped entirely for a dead worker."""
        alive = rep.transport.alive()
        victims = []
        for fid in sorted(self._requests):
            rec = self._requests[fid]
            if rec.replica != rep.idx:
                continue
            je = self._journal.get(fid)
            if je is None:
                continue
            if je.state in _LIVE_STATES:
                victims.append((rec, list(je.delivered)))
                if alive:
                    # migration, not a terminal end: the worker keeps
                    # the span open (migrate_cancel sets
                    # trace_keep_open before the local unwind)
                    try:
                        rep.transport.migrate_cancel(rec.rid)
                    except Exception:   # noqa: BLE001 — best effort
                        pass
            elif (je.state == "failed"
                  and fid not in rep.burst_failed_mark):
                victims.append((rec, list(je.delivered)))
                if self.tracer is not None:
                    # the forwarded burst-failure end already closed
                    # this span; the migration supersedes it (if the
                    # end record never made it over the pipe before
                    # the death, reopen is a harmless no-op)
                    self.tracer.reopen_request(rec.trace_id)
        if self.tracer is not None:
            self.tracer.event("failover", pid=FLEET_PID,
                              replica=rep.idx, victims=len(victims))
        for rec, toks in victims:
            self._migrate(rec, toks)

    def _migrate(self, rec: _FleetRequest, out_tokens: List[int]):
        """Re-enqueue one drained request on the best healthy replica
        (affinity order over prompt ++ history — the history's blocks
        may be cache-hot somewhere). adopt_request bypasses overload
        shedding, so the first candidate accepts; greedy continuation
        is token-identical by the no-sample recompute contract."""
        ctx = (np.concatenate([rec.prompt,
                               np.asarray(out_tokens, np.int32)])
               if out_tokens else rec.prompt)
        order, _ = self._ranked(ctx, rec.sampling,
                                exclude=(rec.replica,))
        for target in order:
            try:
                rid = target.transport.adopt_request(
                    rec.prompt, rec.sampling, out_tokens=out_tokens,
                    t_submit=rec.t_submit, trace_id=rec.trace_id)
            except Exception:   # noqa: BLE001 — a refusing candidate
                # (heterogeneous fleet: adapter not registered there,
                # tighter pool geometry) must not abort the drain: the
                # remaining victims still need their migration, and
                # step()'s never-raises contract covers drains too
                continue
            if self.tracer is not None:
                self.tracer.event(
                    "migrate", trace=rec.trace_id, pid=FLEET_PID,
                    fid=rec.fid, src=rec.replica, dst=target.idx,
                    history=len(out_tokens))
            rec.rid = rid
            rec.replica = target.idx
            rec.migrations += 1
            self.migrated_requests += 1
            je = self._journal.get(rec.fid)
            if je is not None:
                # the adopted request's history IS the harvested
                # tokens: re-anchor the watermark so the new owner's
                # deliveries extend from exactly here
                je.delivered = [int(t) for t in out_tokens]
                je.state = "queued"
                je.error = None
            return
        # no candidate accepted (fleet down / nowhere fits): the
        # request stays terminal on the wedged engine — its record
        # still resolves (result() returns the partial tokens, the
        # state reads aborted/failed) and the refusal is COUNTED so
        # a failovers-vs-victims delta is visible in stats
        self.failed_migrations += 1
        je = self._journal.get(rec.fid)
        if je is not None and je.state in _LIVE_STATES:
            # remote owner: record the terminal verdict in the journal
            # so request()/result() answer from it — the dead/respawned
            # worker can no longer speak for this fid
            je.state = "failed"
            je.error = "migration failed"
        if self.tracer is not None:
            self.tracer.event("migration_failed", trace=rec.trace_id,
                              pid=FLEET_PID, fid=rec.fid,
                              src=rec.replica)
            # the drain suppressed the local abort's span end expecting
            # a continuation that never came — close it here
            self.tracer.end_request(rec.trace_id, "failed",
                                    replica=rec.replica,
                                    error="migration failed")

    def _maybe_probation(self, rep: Replica):
        if (self.cooldown_steps is not None
                and self._step_no - rep.wedged_at
                >= self.cooldown_steps):
            rep.state = "probation"
            rep.strikes = 0
            rep.probation_clean = 0
            if self.tracer is not None:
                self.tracer.event("breaker_probation", pid=FLEET_PID,
                                  replica=rep.idx, step=self._step_no)

    # -- supervisor (ISSUE 19) -----------------------------------------------
    def _worker_death(self, rep: Replica, reason: str):
        """A remote replica's PROCESS is gone (pipe EOF / waitpid) or
        beyond trust (heartbeat-silent past the wedge): count the
        exit, wedge + journal-drain it, then respawn if supervised."""
        self.worker_exits += 1
        if self.tracer is not None:
            self.tracer.event("worker_exit", pid=FLEET_PID,
                              replica=rep.idx, reason=reason,
                              step=self._step_no)
        if rep.state != "wedged":
            self._wedge(rep)
        if self.respawn:
            self._respawn(rep)

    def _respawn(self, rep: Replica):
        """Supervisor restart: fresh worker + engine, replayed warmup
        / warmup_programs / seal_programs (the respawned replica must
        serve with a SEALED program set or every dispatch would count
        as an unexpected recompile), then straight onto PROBATION —
        the PR-11 re-admission ladder, no cooldown (the old process is
        gone; there is nothing to cool down)."""
        t0 = time.perf_counter()
        try:
            rep.transport.respawn()
        except Exception as e:  # noqa: BLE001 — a failed respawn
            # leaves the replica wedged; the supervisor does not loop
            if self.tracer is not None:
                self.tracer.event("worker_respawn_failed",
                                  pid=FLEET_PID, replica=rep.idx,
                                  error=type(e).__name__)
            return
        wall = time.perf_counter() - t0
        self.worker_restarts += 1
        rep.state = "probation"
        rep.strikes = 0
        rep.probation_clean = 0
        rep.exh_mark = 0        # fresh engine: counters restart at 0
        rep.disp_mark = 0
        rep.snap_failed_cnt = 0
        rep.burst_failed_mark = frozenset()
        if self.tracer is not None:
            self.tracer.event("worker_respawn", pid=FLEET_PID,
                              replica=rep.idx, step=self._step_no,
                              wall_s=wall)

    # -- stepping ------------------------------------------------------------
    def step(self) -> bool:
        """One fleet iteration: step every non-wedged replica, read its
        health signals, trip the breaker and drain where needed, and
        revive cooled-down replicas onto probation. Returns True while
        any steppable replica has work. Like ServingEngine.step(), this
        never raises on a replica fault — a dying replica becomes a
        drain, not an exception. Process-transport replicas add two
        pre-step liveness gates (process exit, heartbeat silence) and
        a post-step journal update; the in-proc path is the PR-11 loop
        verbatim behind the transport interface."""
        self._step_no += 1
        for rep in self.replicas:
            if rep.state == "wedged":
                self._maybe_probation(rep)
                continue
            tr = rep.transport
            if tr.remote:
                if not tr.alive():
                    # process exit (waitpid): immediate wedge + drain
                    # + respawn — no strike accumulation; a dead
                    # process yields no more evidence
                    self._worker_death(rep, "process_exit")
                    continue
                if rep.strikes == 0:
                    rep.burst_failed_mark = self._failed_fids(rep)
                age = tr.heartbeat_age()
                if (self.heartbeat_timeout_s is not None
                        and age is not None
                        and age > self.heartbeat_timeout_s):
                    # heartbeat-silent: strike (not instant wedge —
                    # one missed beat on a loaded host is evidence,
                    # not proof). The step RPC is SKIPPED: a hung
                    # worker would cost the full RPC deadline
                    self.heartbeat_misses += 1
                    if self.tracer is not None:
                        self.tracer.event(
                            "heartbeat_miss", pid=FLEET_PID,
                            replica=rep.idx, age_s=age)
                    self._strike(rep, 1, rep.burst_failed_mark)
                    if rep.state == "wedged" and self.respawn:
                        # wedged on silence: the process is beyond
                        # trust — kill it and respawn fresh
                        try:
                            tr.kill_worker()
                        except Exception:   # noqa: BLE001
                            pass
                        self._worker_death(rep, "heartbeat")
                    continue
            else:
                eng = rep.engine
                # pre-step failed-set snapshot: only consulted if THIS
                # step opens a strike burst (see _strike). The
                # frozenset is rebuilt only when engine.failed moved
                # since the last build — an O(1) check per step
                # instead of an O(finished) scan of _done; mid-burst
                # (strikes > 0) the burst-start snapshot must stand,
                # so no refresh
                if rep.strikes == 0 \
                        and eng.failed != rep.snap_failed_cnt:
                    rep.burst_failed_mark = self._failed_rids(eng)
                    rep.snap_failed_cnt = eng.failed
            prestep_mark = rep.burst_failed_mark
            acks, ridmap = self._acks_for(rep)
            try:
                res = tr.step(acks)
            except WorkerDied:
                self._worker_death(rep, "process_exit")
                continue
            except TransportError:
                # retries exhausted but the process is alive: fault
                # evidence, same ladder as a dispatch exhaustion
                self._strike(rep, 1, prestep_mark)
                continue
            # journal first, health second: the drain a strike may
            # trigger reads the journal, which must reflect THIS
            # step's deliveries (exactly-once by the ack-base check)
            self._apply_deliveries(res.deliveries, ridmap)
            rep.last_load = res.load
            exh = res.dispatch_exhaustions - rep.exh_mark
            rep.exh_mark = res.dispatch_exhaustions
            disp = res.device_dispatches - rep.disp_mark
            rep.disp_mark = res.device_dispatches
            stalled = (self.stall_timeout_s is not None
                       and res.wall > self.stall_timeout_s)
            if res.raised or exh > 0 or stalled:
                self._strike(rep, exh, prestep_mark)
            elif disp > 0:
                # clean step WITH device activity: real evidence of
                # health. Idle steps prove nothing — they neither
                # strike nor forgive (a replica that failed its whole
                # queue and went quiet must not launder its record)
                rep.strikes = 0
                if rep.state == "probation":
                    rep.probation_clean += 1
                    if rep.probation_clean >= self.probation_steps:
                        rep.state = "healthy"
                        if self.tracer is not None:
                            self.tracer.event(
                                "breaker_promote", pid=FLEET_PID,
                                replica=rep.idx, step=self._step_no)
        if self.tracer is not None:
            # fleet counter tracks (ISSUE 14): per-replica load on the
            # replica's own track, fleet health on the fleet track —
            # the resource timeline next to the request spans
            for rep in self.replicas:
                self.tracer.counter(
                    "load",
                    (rep.last_load if rep.transport.remote
                     else self._load(rep.engine)),
                    pid=rep.idx)
            self.tracer.counter(
                "healthy_replicas",
                sum(1 for rep in self.replicas
                    if rep.state == "healthy"), pid=FLEET_PID)
        return self.has_work

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        while self.step():
            pass
        out = {}
        for fid in list(self._requests):
            try:
                out[fid] = self.result(fid)
            except KeyError:
                pass
        return out

    def warmup(self, prompt_len: Optional[int] = None,
               seal_programs: bool = False):
        """Warm every replica's compiled programs, then reset stats so
        warmup traffic never pollutes the fleet numbers.
        ``seal_programs=True`` additionally grid-warms and SEALS each
        replica's program set (ServingEngine.warmup contract)."""
        for rep in self.replicas:
            if rep.state != "wedged":
                rep.transport.warmup(prompt_len,
                                     seal_programs=seal_programs)
        self.clear_finished()

    def warmup_programs(self, max_width: Optional[int] = None):
        """Grid-compile every replica's reachable program set by
        direct invocation (no scheduler traffic, no PRNG keys — see
        ServingEngine.warmup_programs). On the process transport this
        call (like warmup/seal) is recorded by the transport and
        REPLAYED into a respawned worker, so a supervisor restart
        comes back with the same compiled+sealed program set."""
        for rep in self.replicas:
            if rep.state != "wedged":
                rep.transport.warmup_programs(max_width)

    def seal_programs(self):
        """Seal every healthy replica's program set: any later compile
        counts in that replica's unexpected_recompiles and the fleet
        rollup — the chaos dp leg asserts the sum stays zero. A WEDGED
        replica is skipped exactly like warmup_programs skips it:
        sealing it cold would turn the recovered replica's legitimate
        grid compiles into false retrace verdicts."""
        for rep in self.replicas:
            if rep.state != "wedged":
                rep.transport.seal_programs()

    # -- stats ---------------------------------------------------------------
    def _journal_bytes(self) -> int:
        """Approximate resident size of the failover journal — the
        cost of exactly-once delivery, surfaced so capacity planning
        can see it (ISSUE 19). Prompt array + 4B/delivered token +
        a fixed per-entry overhead for the dataclass + dict slot."""
        total = 0
        for fid, je in self._journal.items():
            rec = self._requests.get(fid)
            if rec is not None and rec.prompt is not None:
                total += int(getattr(rec.prompt, "nbytes",
                                     4 * len(rec.prompt)))
            total += 4 * len(je.delivered) + 96
        return total

    def stats(self) -> dict:
        """Fleet rollup + per-replica breakdown.

        ``fleet`` carries the routing counters (affinity_hits / spills
        / failovers / migrated_requests / shed_requests — all reset by
        clear_finished), goodput (tokens delivered by successfully
        finished requests — the PR-4 degradation metric, fleet-wide)
        and TRUE fleet ITL percentiles computed over the union of every
        replica's raw inter-token samples (percentiles don't average;
        the per-replica stats() percentiles are reported alongside).
        ``replicas`` is each engine's own stats() plus its health
        record."""
        bundles = [rep.transport.stats_bundle()
                   for rep in self.replicas]
        snaps = [b["snapshot"] for b in bundles]
        itls = Reservoir.merge(
            [(p[0], p[1]) for s in snaps for p in s["itl_parts"]],
            k=ServingEngine.ITL_RESERVOIR_K)
        hit = sum(s["prefix_hit_tokens"] for s in snaps)
        query = sum(s["prefix_query_tokens"] for s in snaps)
        migrated_done = 0
        for fid, rec in self._requests.items():
            if rec.migrations > 0:
                rep = self.replicas[rec.replica]
                if rep.transport.remote:
                    je = self._journal.get(fid)
                    if je is not None and je.state == "done":
                        migrated_done += 1
                    continue
                req = rep.engine._find_request(rec.rid)
                if req is not None and req.state == "done":
                    migrated_done += 1
        fleet = {
            "replicas": self.dp,
            "healthy_replicas": sum(1 for rep in self.replicas
                                    if rep.state == "healthy"),
            "wedged_replicas": sum(1 for rep in self.replicas
                                   if rep.state == "wedged"),
            "routed_requests": self.routed_requests,
            "affinity_hits": self.affinity_hits,
            "affinity_hit_rate": (self.affinity_hits
                                  / self.routed_requests
                                  if self.routed_requests else 0.0),
            "spills": self.spills,
            "failovers": self.failovers,
            "migrated_requests": self.migrated_requests,
            "migrated_done": migrated_done,
            "failed_migrations": self.failed_migrations,
            # FLEET-level refusals only: a per-replica shed that spilled
            # to another replica was served, not shed (the per-replica
            # counts stay visible in the replicas list)
            "shed_requests": self.shed_requests,
            "finished": sum(s["finished"] for s in snaps),
            "generated_tokens": sum(s["generated_tokens"]
                                    for s in snaps),
            "goodput_tokens": sum(s["goodput_tokens"]
                                  for s in snaps),
            "itl_p50_s": (float(np.quantile(itls, 0.50))
                          if itls else None),
            "itl_p99_s": (float(np.quantile(itls, 0.99))
                          if itls else None),
            "preemptions": sum(s["preemptions"] for s in snaps),
            "aborted": sum(s["aborted"] for s in snaps),
            "failed": sum(s["failed"] for s in snaps),
            "retries": sum(s["retries"] for s in snaps),
            "dispatch_exhaustions": sum(s["dispatch_exhaustions"]
                                        for s in snaps),
            "device_dispatches": sum(s["device_dispatches"]
                                     for s in snaps),
            "prefix_cache_hit_rate": hit / query if query else 0.0,
            # -- program observatory (ISSUE 14) -----------------------
            # fleet-wide compile ledger: the chaos dp leg asserts the
            # unexpected sum stays zero after sealing
            "program_compiles": sum(s["program_compiles"]
                                    for s in snaps),
            "unexpected_recompiles": sum(s["unexpected_recompiles"]
                                         for s in snaps),
            # -- process fleet (ISSUE 19) -----------------------------
            # supervisor + transport health, all reset by
            # clear_finished like every counter family above
            "worker_exits": self.worker_exits,
            "worker_restarts": self.worker_restarts,
            "heartbeat_misses": self.heartbeat_misses,
            "rpc_retries": sum(rep.transport.rpc_retries
                               for rep in self.replicas),
            "journal_requests": len(self._journal),
            "journal_bytes": self._journal_bytes(),
        }
        per = []
        for rep, bundle in zip(self.replicas, bundles):
            st = dict(bundle["stats"])
            st["replica"] = rep.idx
            st["state"] = rep.state
            st["wedges"] = rep.wedges
            st["load"] = (bundle["snapshot"]["load"]
                          if rep.transport.remote
                          else self._load(rep.engine))
            per.append(st)
            if self.tracer is not None and rep.transport.remote \
                    and bundle["stats"]:
                # a worker's engine.stats() published into ITS OWN
                # registry; mirror the numeric view into the parent so
                # trace_report and the gate read one registry
                self.tracer.metrics.publish(
                    "engine" if rep.idx == 0 else f"engine{rep.idx}",
                    bundle["stats"])
        if self._slo_policies or any("slo" in st for st in per):
            # per-replica SLO headroom rollup — the input SLO-aware
            # routing needs (ROADMAP 1): route a deadline class to the
            # replica with the most headroom for its policy. min
            # headroom per policy says how close the FLEET is to
            # paging; a wedged replica reports no headroom entry
            headroom: Dict[str, Dict[str, float]] = {}
            for rep, st in zip(self.replicas, per):
                slo = st.get("slo")
                if not slo:
                    continue
                for pname, pol in slo["policies"].items():
                    headroom.setdefault(pname, {})[str(rep.idx)] = \
                        pol["headroom"]
            fleet["slo"] = {
                "headroom": headroom,
                "min_headroom": {
                    pname: min(vals.values())
                    for pname, vals in headroom.items() if vals}}
        if self.tracer is not None:
            # the unified registry mirrors the fleet rollup under
            # "fleet.*"; each engine's stats() call above published its
            # own view under its per-replica namespace ("engine" for
            # replica 0, "engine1"... beyond — no overwriting)
            self.tracer.metrics.publish("fleet", fleet)
            for pname, vals in fleet.get("slo", {}).get(
                    "headroom", {}).items():
                for ridx, h in vals.items():
                    self.tracer.metrics.set_gauge(
                        f"fleet.slo.{pname}.r{ridx}.headroom",
                        float(h))
        return {"fleet": fleet, "replicas": per}

    def clear_finished(self):
        """Fleet-wide counter reset (the clear_finished contract every
        counter family honors): every replica's clear_finished plus the
        routing/failover counters; terminal fleet records are dropped
        with their engine records (live requests keep their mapping)."""
        for rep in self.replicas:
            if rep.transport.remote:
                try:
                    rep.transport.clear_finished()
                except TransportError:
                    pass            # dead worker: nothing to clear
                # the worker's clear_finished zeroed the engine
                # counters the watermarks track, so the parent-side
                # watermarks follow to zero
                rep.exh_mark = 0
                rep.disp_mark = 0
                rep.snap_failed_cnt = 0
                rep.transport.rpc_retries = 0
            else:
                rep.engine.clear_finished()
                rep.exh_mark = rep.engine.dispatch_exhaustions
                rep.disp_mark = rep.engine.device_dispatches
                rep.snap_failed_cnt = rep.engine.failed
            rep.burst_failed_mark = frozenset()
        self.routed_requests = 0
        self.affinity_hits = 0
        self.spills = 0
        self.failovers = 0
        self.migrated_requests = 0
        self.failed_migrations = 0
        self.shed_requests = 0
        self.worker_exits = 0
        self.worker_restarts = 0
        self.heartbeat_misses = 0
        live = {}
        for fid, rec in self._requests.items():
            rep = self.replicas[rec.replica]
            if rep.transport.remote:
                je = self._journal.get(fid)
                if je is not None and je.state in _LIVE_STATES:
                    live[fid] = rec
            elif rep.engine._find_request(rec.rid) is not None:
                live[fid] = rec
        self._requests = live
        # terminal journal entries go with their fleet records: the
        # journal is a FAILOVER ledger, not an archive — exactly-once
        # needs it only while the request can still produce tokens
        self._journal = {fid: je for fid, je in self._journal.items()
                         if fid in live}

    # -- shutdown (ISSUE 19) -------------------------------------------------
    def close(self):
        """Tear the fleet down: close every transport (in-proc engines
        settle their in-flight requests; process workers get a close
        RPC then join, escalating to kill on a hung worker). Safe to
        call twice, safe to call on a half-dead fleet — shutdown is
        the one path that must never raise."""
        if self._closed:
            return
        self._closed = True
        for rep in self.replicas:
            try:
                rep.transport.close()
            except Exception:       # noqa: BLE001 — best-effort
                pass

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
