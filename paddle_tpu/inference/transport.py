"""Replica transports: how the fleet Router drives a ServingEngine
(ISSUE 19 — ROADMAP item 1, the process-isolation increment).

PR 11's Router stepped R engines inside ONE host process: a wedged XLA
runtime, a segfaulting extension or an OOM-killed worker takes the
Router (and every other replica) down with it. This module puts an
interface between the Router and its engines so the failure domain is
a CHOICE:

- ``InProcTransport`` — the engine lives in the Router's process, the
  transport methods are direct delegation. This is the default and is
  bitwise-identical to the PR-11 behavior: same call sites, same
  expressions, same ordering.
- ``ProcTransport`` — the engine lives in a SPAWNED worker process
  (spawn, never fork: JAX/XLA hold live threads) driving a serialized
  command loop over a duplex pipe. One RPC is ``(mid, verb, payload)``
  -> ``(mid, status, result)``; a reader thread routes replies by
  message id, so heartbeats and replies share one pipe without
  head-of-line confusion.

Exactly-once RPC: every verb is made retry-idempotent by a worker-side
REPLY CACHE keyed on message id — a retry (timeout, dropped response)
re-sends the SAME mid and the worker answers from the cache without
re-executing. This is what makes ``step`` safe to retry: naively
re-running a timed-out step would advance the engine twice and
double-deliver tokens. The Router's journal ack watermarks compose with
this: a ``step`` RPC carries ``{rid: n_delivered}`` acks and the reply
carries only tokens BEYOND each ack plus the request's state, so the
Router extends its journal exactly once no matter how many times the
reply crosses the pipe.

Liveness is TWO signals, deliberately separate:

- heartbeat: a worker-side thread sends ``("hb", t)`` every
  ``heartbeat_interval_s`` — parent ``last_hb`` is updated ONLY by
  heartbeat messages (never by RPC replies), so a worker whose main
  loop still answers but whose process is otherwise hung (paused hb
  thread = the test hook) is detectable, and a fully hung process
  stops the clock immediately.
- process exit: ``alive()`` reads the child's exitcode (waitpid
  semantics); the reader thread converts pipe EOF into ``WorkerDied``
  on every pending RPC instantly, so a SIGKILL'd worker fails fast
  instead of waiting out the RPC deadline.

Telemetry forwarding: a traced worker owns a child Tracer whose ids
start at a per-(replica, generation) disjoint base; ``step`` /
``stats_bundle`` replies piggyback the records appended since the last
drain and the parent ingests them (ring + registry mirror), so the
fleet trace stays ONE Perfetto file with migrated request spans
crossing process boundaries. perf_counter is CLOCK_MONOTONIC on Linux
(shared across processes); a ping-measured offset is applied only if
the clocks visibly disagree.

The chaos hooks mirror utils.chaos: ``inject_kill()`` asks the worker
to SIGKILL itself (the hard-death analogue of ``wedge()``), and a
parent-side ``fault_hook("send"/"recv", verb)`` lets a seeded
ChaosMonkey drop/delay RPCs so the retry/backoff path is exercised
deterministically.
"""
from __future__ import annotations

import itertools
import os
import signal
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "TransportError", "RPCTimeout", "WorkerDied", "ReplicaTransport",
    "InProcTransport", "ProcTransport", "WorkerSpec", "RequestView",
    "StepResult",
]


class TransportError(RuntimeError):
    """Base of every transport-level failure (timeout, torn pipe,
    injected drop). Application errors raised BY the engine cross the
    wire as typed replies and re-raise as their own types — they are
    never TransportError and are never retried."""


class RPCTimeout(TransportError):
    """An RPC exceeded its per-call deadline."""


class WorkerDied(TransportError):
    """The worker process exited (pipe EOF / waitpid) — retrying is
    pointless; the Router turns this into a wedge + respawn."""


@dataclass
class RequestView:
    """Cross-process stand-in for serving.Request: the fields the
    Router/harness read (state machine position, generated tokens,
    fault reason). Duck-types the live object for remote replicas."""
    req_id: int
    state: str
    out_tokens: List[int]
    error: Optional[str] = None
    trace_id: Optional[int] = None


@dataclass
class StepResult:
    """One engine step's health + delivery report. Counters are
    CUMULATIVE engine counters (the Router keeps watermarks);
    ``deliveries`` is one entry per acked request: tokens beyond the
    ack watermark plus the post-step state."""
    wall: float
    raised: bool
    dispatch_exhaustions: int
    device_dispatches: int
    failed: int
    deliveries: List[dict]
    load: int
    has_work: bool


# -- shared host-side readers (Router-process AND worker-process) ------------

def _engine_load(eng) -> int:
    """Host-side load proxy: live requests (queued + slotted)."""
    return len(eng._queue) + sum(1 for s in eng._slots if s is not None)


def _engine_coverage(eng, prompt, salt) -> int:
    """Cached chain-hash coverage of ``prompt``, in tokens — the PR-1
    index walk, pure host-side."""
    if not eng.prefix_caching:
        return 0
    cache = eng.dec.cache
    return len(cache.match_prefix(prompt, salt)) * cache.block_size


def collect_deliveries(eng, acks: Dict[int, int]) -> List[dict]:
    """Per acked request: tokens beyond the ack watermark + state.
    Pure host reads (no device traffic); ``base`` echoes the ack so the
    Router's journal extension is idempotent under RPC retry."""
    out = []
    for rid, base in acks.items():
        base = int(base)
        req = eng._find_request(rid)
        if req is None:
            out.append({"rid": int(rid), "base": base, "tokens": [],
                        "state": "gone", "error": None})
            continue
        out.append({"rid": int(rid), "base": base,
                    "tokens": [int(t) for t in req.out_tokens[base:]],
                    "state": req.state, "error": req.error})
    return out


def _engine_snapshot(eng) -> dict:
    """The attribute reads Router.stats() aggregates across replicas —
    gathered into one picklable dict so the remote path ships it in a
    single RPC and the in-proc path reads the same shape."""
    cache = eng.dec.cache
    live = [x for r in eng._slots if r is not None for x in r.itls]
    return {
        "itl_parts": [(list(eng._itl_res.samples), eng._itl_res.n),
                      (live, len(live))],
        "goodput_tokens": sum(len(r.out_tokens)
                              for r in eng._done.values()
                              if r.state == "done"),
        "finished": sum(1 for r in eng._done.values()
                        if r.state == "done"),
        "prefix_hit_tokens": cache.prefix_hit_tokens,
        "prefix_query_tokens": cache.prefix_query_tokens,
        "generated_tokens": eng.generated_tokens,
        "preemptions": eng.preemptions,
        "aborted": eng.aborted,
        "failed": eng.failed,
        "retries": eng.retries,
        "dispatch_exhaustions": eng.dispatch_exhaustions,
        "device_dispatches": eng.device_dispatches,
        "program_compiles": eng.program_compiles,
        "unexpected_recompiles": eng.unexpected_recompiles,
        "load": _engine_load(eng),
    }


class ReplicaTransport:
    """The verbs the Router needs from a replica. ``remote`` is the
    single branch point the Router consults for the places where the
    two transports genuinely differ (journal-based drain, view
    fallback, death detection) — everything else goes through these
    methods on both."""

    remote = False
    rpc_retries = 0          # transient-RPC retries taken (remote only)

    # request surface
    def add_request(self, prompt, sp) -> Tuple[int, Optional[int]]:
        raise NotImplementedError

    def adopt_request(self, prompt, sp, out_tokens, t_submit,
                      trace_id) -> int:
        raise NotImplementedError

    def cancel(self, rid: int) -> bool:
        raise NotImplementedError

    def result(self, rid: int) -> np.ndarray:
        raise NotImplementedError

    def view(self, rid: int):
        raise NotImplementedError

    # routing inputs
    def match_coverage(self, prompt, salt) -> int:
        raise NotImplementedError

    def load(self) -> int:
        raise NotImplementedError

    def has_work(self) -> bool:
        raise NotImplementedError

    # stepping / health
    def step(self, acks: Dict[int, int]) -> StepResult:
        raise NotImplementedError

    def alive(self) -> bool:
        return True

    def heartbeat_age(self) -> Optional[float]:
        return None

    # lifecycle
    def warmup(self, prompt_len=None, seal_programs=False):
        raise NotImplementedError

    def warmup_programs(self, max_width=None):
        raise NotImplementedError

    def seal_programs(self):
        raise NotImplementedError

    def stats_bundle(self) -> dict:
        raise NotImplementedError

    def clear_finished(self):
        raise NotImplementedError

    def close(self):
        raise NotImplementedError


class InProcTransport(ReplicaTransport):
    """The PR-11 behavior behind the transport interface: every method
    is the exact expression the Router used to inline — same reads,
    same exception flow, same ordering — so ``transport="inproc"`` is
    bitwise-identical to the pre-transport Router."""

    remote = False

    def __init__(self, engine):
        self.engine = engine

    def add_request(self, prompt, sp):
        rid = self.engine.add_request(prompt, sp)
        req = self.engine._find_request(rid)
        return rid, (req.trace_id if req is not None else None)

    def adopt_request(self, prompt, sp, out_tokens, t_submit, trace_id):
        return self.engine.adopt_request(
            prompt, sp, out_tokens=out_tokens, t_submit=t_submit,
            trace_id=trace_id)

    def cancel(self, rid):
        return self.engine.cancel(rid)

    def result(self, rid):
        return self.engine.result(rid)

    def view(self, rid):
        return self.engine._find_request(rid)

    def match_coverage(self, prompt, salt):
        return _engine_coverage(self.engine, prompt, salt)

    def load(self):
        return _engine_load(self.engine)

    def has_work(self):
        return self.engine.has_work

    def step(self, acks):
        eng = self.engine
        t0 = time.perf_counter()
        raised = False
        try:
            eng.step()
        except Exception:       # noqa: BLE001 — step() never raises by
            raised = True       # contract; a wedge IS the never case
        wall = time.perf_counter() - t0
        return StepResult(
            wall=wall, raised=raised,
            dispatch_exhaustions=eng.dispatch_exhaustions,
            device_dispatches=eng.device_dispatches,
            failed=eng.failed,
            deliveries=collect_deliveries(eng, acks),
            load=_engine_load(eng), has_work=eng.has_work)

    def warmup(self, prompt_len=None, seal_programs=False):
        self.engine.warmup(prompt_len, seal_programs=seal_programs)

    def warmup_programs(self, max_width=None):
        self.engine.warmup_programs(max_width)

    def seal_programs(self):
        self.engine.seal_programs()

    def stats_bundle(self):
        return {"snapshot": _engine_snapshot(self.engine),
                "stats": self.engine.stats()}

    def clear_finished(self):
        self.engine.clear_finished()

    def close(self):
        self.engine.close()


# -- process transport --------------------------------------------------------

@dataclass
class WorkerSpec:
    """Everything a spawned worker needs to build its engine. Must be
    picklable: the default path ships the MODEL ITSELF (a tiny-config
    model pickles in milliseconds; spawn re-imports the framework in
    the child anyway), the factory path ships a module-level callable
    ``f(replica_idx, devices)``. Device objects never cross the pipe —
    a tp>1 worker recomputes its own SpecLayout row child-side."""
    model: Any = None
    factory: Optional[Callable] = None
    dp: int = 1
    tp: int = 1
    engine_kwargs: Dict[str, Any] = field(default_factory=dict)
    slo_policies: tuple = ()
    traced: bool = False


def _build_worker_engine(spec: WorkerSpec, replica_id: int):
    devices = None
    if spec.tp > 1:
        from ..distributed.spec_layout import SpecLayout
        devices = SpecLayout().fleet_device_slices(
            spec.dp, spec.tp)[replica_id]
    if spec.factory is not None:
        return spec.factory(replica_id, devices)
    from .serving import ServingEngine
    kw = dict(spec.engine_kwargs)
    if spec.slo_policies:
        from ..utils.telemetry import SLOMonitor
        kw["slo"] = SLOMonitor(list(spec.slo_policies))
    return ServingEngine(spec.model, tp=spec.tp, devices=devices, **kw)


# bound on the worker's exactly-once reply cache: must cover every
# message id a retry can still reference (retries are per-call and
# bounded, so a handful suffices; 64 is paranoid headroom)
_REPLY_CACHE = 64


def _worker_main(conn, spec: WorkerSpec, replica_id: int,
                 hb_interval: float, id_base: int):
    """Worker process entry: build the engine, start the heartbeat
    thread, then serve the command loop until ``close`` / pipe EOF.
    Runs in the SPAWNED child — must stay module-level picklable."""
    send_lock = threading.Lock()
    stop = threading.Event()

    def _send(msg):
        with send_lock:
            try:
                conn.send(msg)
            except Exception:   # noqa: BLE001 — parent went away
                stop.set()

    tracer = None
    try:
        eng = _build_worker_engine(spec, replica_id)
        if spec.traced:
            from ..utils.telemetry import Tracer
            tracer = Tracer(id_base=id_base)
            eng.set_telemetry(tracer, replica_id=replica_id)
    except Exception as e:      # noqa: BLE001 — report, don't hang
        _send(("ready", {"error": f"{type(e).__name__}: {e}"}))
        return

    hb_state = {"pause_until": 0.0}

    def _hb_loop():
        while not stop.wait(hb_interval):
            if time.perf_counter() >= hb_state["pause_until"]:
                _send(("hb", time.perf_counter()))

    threading.Thread(target=_hb_loop, daemon=True).start()
    _send(("ready", {"pid": os.getpid()}))

    replies: OrderedDict = OrderedDict()
    tel_mark = 0
    monkey = None

    def _drain_tel(res: dict):
        nonlocal tel_mark
        if tracer is not None:
            recs, tel_mark = tracer.drain_since(tel_mark)
            res["tel"] = recs
        return res

    while not stop.is_set():
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if not (isinstance(msg, tuple) and msg and msg[0] == "cmd"):
            continue
        _, mid, verb, payload = msg
        if mid in replies:
            # exactly-once: a retried mid re-sends the cached reply
            # WITHOUT re-executing (the step that already ran must not
            # run twice)
            _send(replies[mid])
            continue
        if verb == "chaos_kill":
            # hard death, fire-and-forget: no reply ever
            if monkey is not None:
                monkey.kill()
            os.kill(os.getpid(), signal.SIGKILL)
        try:
            if verb == "ping":
                result = time.perf_counter()
            elif verb == "add_request":
                rid = eng.add_request(payload["prompt"], payload["sp"])
                req = eng._find_request(rid)
                result = (rid, (req.trace_id if req is not None
                                else None))
            elif verb == "adopt_request":
                result = eng.adopt_request(
                    payload["prompt"], payload["sp"],
                    out_tokens=payload["out_tokens"],
                    t_submit=payload["t_submit"],
                    trace_id=payload["trace_id"])
            elif verb == "step":
                t0 = time.perf_counter()
                raised = False
                try:
                    eng.step()
                except Exception:   # noqa: BLE001 — never by contract
                    raised = True
                result = _drain_tel({
                    "wall": time.perf_counter() - t0, "raised": raised,
                    "dispatch_exhaustions": eng.dispatch_exhaustions,
                    "device_dispatches": eng.device_dispatches,
                    "failed": eng.failed,
                    "deliveries": collect_deliveries(
                        eng, payload["acks"]),
                    "load": _engine_load(eng),
                    "has_work": eng.has_work})
            elif verb == "cancel":
                result = eng.cancel(payload)
            elif verb == "migrate_cancel":
                req = eng._find_request(payload)
                result = False
                if req is not None and req.state in (
                        "queued", "prefilling", "running"):
                    # migration, not a terminal end: keep the span open
                    req.trace_keep_open = True
                    try:
                        result = eng.cancel(payload)
                    except Exception:   # noqa: BLE001 — best effort
                        result = False
            elif verb == "result":
                result = eng.result(payload)
            elif verb == "view":
                req = eng._find_request(payload)
                result = None if req is None else {
                    "req_id": req.req_id, "state": req.state,
                    "out_tokens": [int(t) for t in req.out_tokens],
                    "error": req.error, "trace_id": req.trace_id}
            elif verb == "match_coverage":
                result = _engine_coverage(
                    eng, payload["prompt"], payload["salt"])
            elif verb == "load":
                result = _engine_load(eng)
            elif verb == "has_work":
                result = eng.has_work
            elif verb == "warmup":
                eng.warmup(payload["prompt_len"],
                           seal_programs=payload["seal"])
                result = None
            elif verb == "warmup_programs":
                eng.warmup_programs(payload)
                result = None
            elif verb == "seal_programs":
                eng.seal_programs()
                result = None
            elif verb == "stats_bundle":
                result = _drain_tel({
                    "snapshot": _engine_snapshot(eng),
                    "stats": eng.stats()})
            elif verb == "clear_finished":
                eng.clear_finished()
                result = None
            elif verb == "debug_check":
                eng.dec.cache.debug_check()
                if eng.lora is not None:
                    eng._debug_lora_check()
                result = True
            elif verb == "chaos_attach":
                from ..utils.chaos import ChaosMonkey
                monkey = ChaosMonkey(**payload).attach(eng)
                result = None
            elif verb == "chaos_counts":
                result = dict(monkey.counts) if monkey is not None \
                    else {}
            elif verb == "chaos_wedge":
                if monkey is not None:
                    monkey.wedge()
                result = None
            elif verb == "hb_pause":
                hb_state["pause_until"] = time.perf_counter() \
                    + float(payload)
                result = None
            elif verb == "close":
                _send(("reply", mid, "ok", None))
                break
            else:
                raise ValueError(f"unknown transport verb {verb!r}")
            reply = ("reply", mid, "ok", result)
        except Exception as e:  # noqa: BLE001 — typed across the wire
            reply = ("reply", mid, "err",
                     (type(e).__name__, str(e)))
        replies[mid] = reply
        while len(replies) > _REPLY_CACHE:
            replies.popitem(last=False)
        _send(reply)
    stop.set()
    try:
        eng.close()
    except Exception:           # noqa: BLE001 — exiting anyway
        pass


class _Waiter:
    __slots__ = ("event", "result")

    def __init__(self):
        self.event = threading.Event()
        self.result = None


# engine exceptions that cross the wire by TYPE (the Router's spill /
# validation / cancel paths catch these); everything else re-raises as
# TransportError subtype RemoteEngineError
def _map_remote_error(etype: str, emsg: str) -> Exception:
    if etype == "EngineOverloaded":
        from .serving import EngineOverloaded
        return EngineOverloaded(emsg)
    if etype == "KeyError":
        return KeyError(emsg)
    if etype == "ValueError":
        return ValueError(emsg)
    if etype == "KVCacheExhausted":
        from ..ops.paged_attention import KVCacheExhausted
        return KVCacheExhausted(emsg)
    return RemoteEngineError(f"{etype}: {emsg}")


class RemoteEngineError(TransportError):
    """An unmapped exception raised by the remote engine."""


class ProcTransport(ReplicaTransport):
    """One replica engine in a spawned worker process.

    RPCs ride a duplex pipe with per-call deadlines and bounded retry
    with exponential backoff (``retry_backoff_s * 2**(attempt-1)``,
    the engine's own _device_call idiom); the worker's reply cache
    makes every retry exactly-once. ``fault_hook(stage, verb)`` — a
    seeded ChaosMonkey.transport_fault — may raise before send or
    after receive to model dropped RPCs deterministically."""

    remote = True

    # verbs that may compile program grids: give them a generous floor
    _LONG_VERBS = ("warmup", "warmup_programs", "seal_programs")

    def __init__(self, spec: WorkerSpec, *, replica_id: int = 0,
                 tracer=None, rpc_timeout_s: float = 120.0,
                 rpc_retries: int = 2, retry_backoff_s: float = 0.05,
                 heartbeat_interval_s: float = 0.25,
                 spawn_timeout_s: float = 300.0,
                 fault_hook=None):
        self.spec = spec
        self.replica_id = int(replica_id)
        self.tracer = tracer
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.max_rpc_retries = max(0, int(rpc_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.fault_hook = fault_hook
        self.rpc_retries = 0
        self.generation = 0
        # lifecycle calls replayed on respawn, in order (a fresh
        # engine must re-warm and re-seal or every post-respawn
        # dispatch compiles — and counts as an unexpected recompile)
        self._warm_calls: List[Tuple[str, Any]] = []
        self._chaos_cfg: Optional[dict] = None
        self._last_has_work = False
        self._last_bundle: Optional[dict] = None
        self._closed = False
        self._spawn()

    # -- lifecycle -----------------------------------------------------------
    def _spawn(self):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")   # fork is unsafe under JAX
        parent_conn, child_conn = ctx.Pipe(duplex=True)
        self.generation += 1
        # disjoint trace-id ranges per (replica, generation): merged
        # exports must never collide ids across processes or respawns
        id_base = ((self.replica_id + 1) * 1_000_000_000
                   + (self.generation - 1) * 50_000_000)
        proc = ctx.Process(
            target=_worker_main,
            args=(child_conn, self.spec, self.replica_id,
                  self.heartbeat_interval_s, id_base),
            daemon=True,
            name=f"paddle-replica{self.replica_id}"
                 f"-g{self.generation}")
        proc.start()
        child_conn.close()
        self._conn = parent_conn
        self._proc = proc
        self._dead = False
        self._closed = False
        self._pending: Dict[int, _Waiter] = {}
        self._plock = threading.Lock()
        self._send_lock = threading.Lock()
        self._mids = itertools.count(1)
        self._last_hb = time.perf_counter()
        self._ready = threading.Event()
        self._ready_info: dict = {}
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"transport-reader-r{self.replica_id}")
        self._reader.start()
        if not self._ready.wait(self.spawn_timeout_s) or self._dead:
            self._teardown(kill=True)
            raise TransportError(
                f"replica {self.replica_id} worker failed to start "
                f"within {self.spawn_timeout_s}s")
        if self._ready_info.get("error"):
            self._teardown(kill=True)
            raise TransportError(
                f"replica {self.replica_id} worker engine build "
                f"failed: {self._ready_info['error']}")
        # clock handshake: perf_counter is CLOCK_MONOTONIC on Linux
        # (shared across processes) — apply a measured offset only if
        # the clocks visibly disagree (cross-platform safety)
        t0 = time.perf_counter()
        tw = self._rpc("ping")
        t1 = time.perf_counter()
        off = (t0 + t1) / 2.0 - tw
        self._ts_offset = off if abs(off) > 0.05 else 0.0

    def _read_loop(self):
        conn = self._conn
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            kind = msg[0]
            if kind == "hb":
                self._last_hb = time.perf_counter()
            elif kind == "ready":
                self._ready_info = msg[1]
                self._ready.set()
            elif kind == "reply":
                _, mid, status, payload = msg
                with self._plock:
                    w = self._pending.get(mid)
                if w is not None:
                    w.result = (status, payload)
                    w.event.set()
        # pipe EOF: the worker died — fail every pending RPC NOW
        # (a SIGKILL'd worker must not cost an RPC deadline)
        self._dead = True
        with self._plock:
            waiters = list(self._pending.values())
        for w in waiters:
            w.result = ("died", None)
            w.event.set()
        self._ready.set()

    def respawn(self):
        """Supervisor restart: tear the dead worker down, spawn a
        fresh one and replay the recorded lifecycle calls (warmup /
        warmup_programs / seal_programs, then the chaos config) so the
        respawned engine serves with a warm, SEALED program set."""
        self._teardown(kill=True)
        self._spawn()
        for verb, payload in list(self._warm_calls):
            self._rpc(verb, payload,
                      timeout=max(600.0, self.rpc_timeout_s))
        if self._chaos_cfg is not None:
            self._rpc("chaos_attach", self._chaos_cfg)
        self._last_has_work = False

    def _teardown(self, kill: bool):
        proc = getattr(self, "_proc", None)
        if proc is None:
            return
        if not kill and not self._dead and proc.is_alive():
            try:
                self._rpc("close", timeout=30.0, retries=0)
            except Exception:   # noqa: BLE001 — escalate below
                pass
        self._dead = True
        try:
            self._conn.close()
        except Exception:       # noqa: BLE001
            pass
        proc.join(timeout=5.0)
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=5.0)
        if proc.is_alive():
            proc.kill()
            proc.join(timeout=5.0)
        reader = getattr(self, "_reader", None)
        if reader is not None and reader is not threading.current_thread():
            reader.join(timeout=5.0)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._teardown(kill=False)

    # -- liveness ------------------------------------------------------------
    def alive(self) -> bool:
        return (not self._dead and self._proc.is_alive())

    def heartbeat_age(self) -> Optional[float]:
        return time.perf_counter() - self._last_hb

    def kill_worker(self):
        """Parent-side SIGKILL (deterministic test hook — the worker
        dies at a point the TEST chooses, not the seeded schedule)."""
        if self._proc.is_alive():
            os.kill(self._proc.pid, signal.SIGKILL)
        self._proc.join(timeout=10.0)

    def inject_kill(self):
        """Ask the worker to SIGKILL ITSELF (ChaosMonkey.kill) — fire
        and forget: no reply will ever come."""
        try:
            with self._send_lock:
                self._conn.send(("cmd", next(self._mids),
                                 "chaos_kill", None))
        except Exception:       # noqa: BLE001 — already dying is fine
            pass

    def hb_pause(self, seconds: float):
        """Pause the worker's heartbeat thread (liveness test hook:
        the main loop keeps answering while the heartbeat goes quiet —
        only a TRUE heartbeat clock can detect this)."""
        self._rpc("hb_pause", float(seconds))

    # -- RPC core ------------------------------------------------------------
    def _verb_timeout(self, verb: str, timeout: Optional[float]):
        if timeout is not None:
            return timeout
        if verb in self._LONG_VERBS:
            return max(600.0, self.rpc_timeout_s)
        return self.rpc_timeout_s

    def _rpc(self, verb: str, payload=None, timeout: Optional[float]
             = None, retries: Optional[int] = None):
        timeout = self._verb_timeout(verb, timeout)
        retries = self.max_rpc_retries if retries is None else retries
        mid = next(self._mids)      # SAME mid across retries: the
        last = None                 # worker's reply cache dedupes
        for attempt in range(retries + 1):
            if attempt:
                self.rpc_retries += 1
                if self.retry_backoff_s > 0:
                    time.sleep(self.retry_backoff_s
                               * (2 ** (attempt - 1)))
            try:
                return self._rpc_once(mid, verb, payload, timeout)
            except WorkerDied:
                raise
            except TransportError as e:
                last = e
            except Exception as e:      # noqa: BLE001 — only the
                # chaos hook's injected drops are retryable; any other
                # exception is a programming error and must surface
                if type(e).__name__ != "InjectedTransportError":
                    raise
                last = e
        raise TransportError(
            f"replica {self.replica_id} rpc {verb!r} failed after "
            f"{retries + 1} attempt(s): {last}") from last

    def _rpc_once(self, mid, verb, payload, timeout):
        if self._dead:
            raise WorkerDied(
                f"replica {self.replica_id} worker is dead")
        hook = self.fault_hook
        if hook is not None:
            hook("send", verb)  # may raise (injected request drop)
        w = _Waiter()
        with self._plock:
            self._pending[mid] = w
        try:
            try:
                with self._send_lock:
                    self._conn.send(("cmd", mid, verb, payload))
            except (OSError, ValueError, BrokenPipeError) as e:
                if self._dead or not self._proc.is_alive():
                    raise WorkerDied(
                        f"replica {self.replica_id} worker died "
                        f"mid-send: {e}") from e
                raise TransportError(f"send failed: {e}") from e
            if not w.event.wait(timeout):
                raise RPCTimeout(
                    f"replica {self.replica_id} rpc {verb!r} timed "
                    f"out after {timeout}s")
        finally:
            with self._plock:
                self._pending.pop(mid, None)
        status, out = w.result
        if status == "died":
            raise WorkerDied(
                f"replica {self.replica_id} worker died during "
                f"{verb!r}")
        if hook is not None:
            hook("recv", verb)  # may raise (injected response drop —
            #                     the retry re-asks; the reply cache
            #                     answers without re-executing)
        if status == "err":
            raise _map_remote_error(*out)
        return out

    # -- request surface -----------------------------------------------------
    def add_request(self, prompt, sp):
        rid, tid = self._rpc("add_request",
                             {"prompt": prompt, "sp": sp})
        self._last_has_work = True
        return rid, tid

    def adopt_request(self, prompt, sp, out_tokens, t_submit,
                      trace_id):
        rid = self._rpc("adopt_request", {
            "prompt": prompt, "sp": sp,
            "out_tokens": list(out_tokens), "t_submit": t_submit,
            "trace_id": trace_id})
        self._last_has_work = True
        return rid

    def cancel(self, rid):
        return self._rpc("cancel", rid)

    def migrate_cancel(self, rid):
        return self._rpc("migrate_cancel", rid)

    def result(self, rid):
        return np.asarray(self._rpc("result", rid), np.int32)

    def view(self, rid):
        v = self._rpc("view", rid)
        return None if v is None else RequestView(**v)

    # -- routing inputs ------------------------------------------------------
    def match_coverage(self, prompt, salt):
        return self._rpc("match_coverage",
                         {"prompt": prompt, "salt": salt})

    def load(self):
        return self._rpc("load")

    def has_work(self):
        # cached from the last step reply (kept True by admissions):
        # an extra idle step is harmless; an RPC per has_work is not
        return self._last_has_work

    # -- stepping ------------------------------------------------------------
    def step(self, acks):
        res = self._rpc("step", {"acks": dict(acks)})
        if self.tracer is not None and res.get("tel"):
            self.tracer.ingest(res["tel"], ts_offset=self._ts_offset)
        self._last_has_work = bool(res["has_work"])
        return StepResult(
            wall=res["wall"], raised=res["raised"],
            dispatch_exhaustions=res["dispatch_exhaustions"],
            device_dispatches=res["device_dispatches"],
            failed=res["failed"], deliveries=res["deliveries"],
            load=res["load"], has_work=res["has_work"])

    # -- lifecycle verbs (recorded for respawn replay) -----------------------
    def warmup(self, prompt_len=None, seal_programs=False):
        payload = {"prompt_len": prompt_len, "seal": bool(seal_programs)}
        self._warm_calls.append(("warmup", payload))
        self._rpc("warmup", payload)

    def warmup_programs(self, max_width=None):
        self._warm_calls.append(("warmup_programs", max_width))
        self._rpc("warmup_programs", max_width)

    def seal_programs(self):
        self._warm_calls.append(("seal_programs", None))
        self._rpc("seal_programs", None)

    def stats_bundle(self):
        try:
            res = self._rpc("stats_bundle")
        except TransportError:
            # dead worker: its counters died with it — the last
            # successful bundle is the honest remainder (the JOURNAL,
            # not stats, is the source of truth for requests)
            if self._last_bundle is not None:
                return self._last_bundle
            return {"snapshot": _EMPTY_SNAPSHOT.copy(), "stats": {}}
        if self.tracer is not None and res.get("tel"):
            self.tracer.ingest(res["tel"], ts_offset=self._ts_offset)
        bundle = {"snapshot": res["snapshot"], "stats": res["stats"]}
        self._last_bundle = bundle
        return bundle

    def clear_finished(self):
        self._rpc("clear_finished")
        self._last_bundle = None

    # -- chaos wiring --------------------------------------------------------
    def chaos_attach(self, **cfg):
        """Build + attach a seeded ChaosMonkey INSIDE the worker (the
        config is recorded and replayed on respawn with the same
        seed)."""
        self._chaos_cfg = dict(cfg)
        self._rpc("chaos_attach", self._chaos_cfg)

    def chaos_counts(self) -> dict:
        return self._rpc("chaos_counts")

    def chaos_wedge(self):
        self._rpc("chaos_wedge")

    def debug_check(self):
        return self._rpc("debug_check")


_EMPTY_SNAPSHOT = {
    "itl_parts": [], "goodput_tokens": 0, "finished": 0,
    "prefix_hit_tokens": 0, "prefix_query_tokens": 0,
    "generated_tokens": 0, "preemptions": 0, "aborted": 0,
    "failed": 0, "retries": 0, "dispatch_exhaustions": 0,
    "device_dispatches": 0, "program_compiles": 0,
    "unexpected_recompiles": 0, "load": 0,
}
