"""Continuous-batching LLM serving engine over the paged KV pool.

Reference: the AnalysisPredictor serving subsystem
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h:100)
plus the block_multihead_attention continuous-decode path
(/root/reference/python/paddle/incubate/nn/functional/
block_multihead_attention.py). The reference composes CUDA kernels under
a pass-optimized executor; the TPU-native equivalent is a *fixed-shape*
scheduler: XLA programs cannot change batch size per step, so continuous
batching becomes a fixed grid of batch slots with per-slot activity —
the same trick the paged pool already plays for sequence length.

Architecture (all shapes static; compiled programs: ONE decode chunk
plus TWO prefill widths per active prompt bucket):
- admission: queued requests prefill into free batch slots, grouped per
  prompt bucket into shared dispatches (width 1 for singles, width
  PREFILL_GROUP for bursts, padded with scratch rows — bounding the
  compile-variant count; right-padding writes its K/V to a reserved
  scratch page, so the pool never sees pad junk; logits are taken at
  the real last token).
- automatic prefix caching (prefix_caching=True, the default): on
  admission the prompt is hashed at block granularity against the
  pool's chain-hash index (PagedKVCache.match_prefix); matched full
  blocks are spliced into the request's block table (ref++, no copy)
  and ONLY the uncovered suffix prefills — bucketed on SUFFIX length,
  RoPE positions and slot mappings offset by n_cached, attention run
  over [gathered prefix pages ++ suffix] (the decoder's
  _prefill_prefix_impl; n_cached is data, so one compiled program per
  (bucket, width) serves every hit length). The worst-case admission
  capacity check credits reusable blocks, so cache hits raise
  effective pool capacity. Requests whose matched blocks are written
  by a prefill admitted in the SAME wave are dispatched in a later
  wave (device program order makes the write visible to the read).
  Retired requests return blocks through the ref-counted path: full
  hashed blocks park in the pool's LRU for future splices and are
  evicted only when the free list runs dry.
- decode: ONE program serves every step — a lax.scan over a
  chunk_size-token schedule (the page/slot schedule is deterministic, so
  the host precomputes it), [max_batch] wide, inactive or finished slots
  aimed at the scratch page and their outputs discarded. Sampling
  (per-slot temperature, engine-static top_k) happens in-program, so
  only [max_batch, chunk] token ids cross the host boundary per chunk.
  Chunking is what makes continuous batching viable on TPU: per-dispatch
  round-trips (hundreds of ms through a remote-compile tunnel, ~10us
  locally) amortize over chunk_size tokens, while admission still
  happens every chunk boundary.
- completion: EOS/max-token slots free their pages (mid-chunk EOS trims
  the tail tokens); the slot admits the next queued request at the next
  chunk boundary.

Weight-only int8 (weight_dtype="int8") stores matmul weights as
per-channel int8 + scale — decode is HBM-bandwidth-bound, so halving
weight bytes is the serving-side quantization that actually pays on TPU.
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .paged_decode import PagedLlamaDecoder

__all__ = ["SamplingParams", "Request", "ServingEngine"]


@dataclass
class SamplingParams:
    """Per-request sampling controls (reference generation surface:
    /root/reference/python/paddle/nn/decode.py:994 dynamic_decode +
    the incubate serving path). temperature<=0 means greedy; top_k=None
    defers to the engine-level top_k default while top_k=0 explicitly
    disables the filter (even against an engine default); top_p=1.0
    and repetition_penalty=1.0 are off. All are PER REQUEST and applied
    in-program (mask-based — no new compile variants per value)."""
    temperature: float = 0.0
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    top_k: Optional[int] = None
    top_p: float = 1.0
    repetition_penalty: float = 1.0

    @property
    def needs_rich_sampling(self) -> bool:
        # an EXPLICIT top_k (including 0, which must be able to override
        # an engine-level default) routes through the per-request path
        return (self.top_k is not None or self.top_p < 1.0
                or self.repetition_penalty != 1.0)


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray                    # [prompt_len] int32
    sampling: SamplingParams
    out_tokens: List[int] = field(default_factory=list)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    state: str = "queued"                 # queued | running | done
    # tokens DISPATCHED (prefill + scheduled decode steps) — may exceed
    # len(out_tokens) while a chunk is in flight or after an EOS cut
    planned: int = 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


def _bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"prompt length {n} exceeds the largest prefill bucket: "
        f"configured prompt_buckets={tuple(buckets)} top out at "
        f"{buckets[-1]} tokens; raise prompt_buckets (or shorten the "
        f"prompt). Oversized prompts are rejected at add_request time "
        f"so they never reach dispatch.")


class ServingEngine:
    """Mixed-length concurrent request serving for a LlamaForCausalLM.

    Usage:
        eng = ServingEngine(model, max_batch_size=8)
        rid = eng.add_request(prompt_ids, SamplingParams(max_new_tokens=64))
        while eng.step():
            pass
        tokens = eng.result(rid)
    """

    def __init__(self, model, max_batch_size: int = 8,
                 num_blocks: int = 512, block_size: int = 16,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256, 512),
                 weight_dtype: Optional[str] = None, top_k: int = 0,
                 chunk_size: int = 8, seed: int = 0,
                 overlap: bool = True, mesh=None,
                 chunk_schedule: Optional[Sequence[int]] = None,
                 prefix_caching: bool = True):
        from .gpt_decode import PagedGPTDecoder
        if isinstance(model, (PagedLlamaDecoder, PagedGPTDecoder)):
            # a prebuilt paged decoder (e.g. PagedLlamaDecoder
            # .from_config for 8B-class weights that must be quantized
            # at load); its pool/quantization choices stand — the
            # num_blocks/block_size/weight_dtype args here are ignored
            self.dec = model
        else:
            self.dec = PagedLlamaDecoder(model, num_blocks=num_blocks,
                                         block_size=block_size,
                                         weight_dtype=weight_dtype,
                                         mesh=mesh)
        self.max_b = int(max_batch_size)
        self.buckets = tuple(sorted(prompt_buckets))
        self.top_k = int(top_k)
        # chunk ladder (adaptive decode granularity): each dispatch
        # picks a rung via _pick_chunk — after warmup, the rung
        # maximizing measured tokens/sec for the current slot budgets
        # (big chunks amortize host round trips; small chunks keep slot
        # turnover and admission prompt). Single-entry schedule (the
        # default) = fixed chunk.
        if chunk_schedule:
            self.chunks = tuple(sorted({max(1, int(c))
                                        for c in chunk_schedule}))
        else:
            self.chunks = (max(1, int(chunk_size)),)
        self.chunk = self.chunks[0]
        # overlap: dispatch decode chunk t+1 (first tokens taken from
        # chunk t's DEVICE output) before fetching chunk t's tokens, so
        # host admission/bookkeeping runs while the device decodes.
        # Falls back to synchronous collection while any active request
        # uses repetition_penalty (its seen-mask needs fetched history).
        self.overlap = bool(overlap)
        self._key = jax.random.PRNGKey(seed)
        cache = self.dec.cache
        # reserve one scratch page: pad-token prefill writes and inactive
        # decode slots land here, never in a live page (a prebuilt
        # decoder reused across engines keeps its existing scratch page)
        if -1 not in cache._tables:
            cache.allocate(-1, 1)
        self._scratch_block = cache._tables[-1][0]
        self._scratch_slot = self._scratch_block * cache.block_size
        # automatic prefix caching: block-granular KV reuse on admission
        # (needs the decoder's suffix-prefill program — prebuilt
        # decoders without one fall back to full prefills)
        self.prefix_caching = bool(prefix_caching) and \
            hasattr(self.dec, "_prefill_prefix_impl")
        # static prefix-gather width: a hit prefix is < the prompt, and
        # prompts are bounded by the largest bucket
        self._prefix_pages = -(-self.buckets[-1] // cache.block_size)
        self._debug_pool = os.environ.get(
            "PADDLE_TPU_POOL_DEBUG", "") not in ("", "0")

        self._slots: List[Optional[Request]] = [None] * self.max_b
        self._last_tok = np.zeros(self.max_b, np.int32)
        self._queue: deque = deque()
        self._done: Dict[int, Request] = {}
        self._ids = itertools.count()
        self.decode_steps = 0
        self.generated_tokens = 0
        # async pipeline state (overlap mode)
        self._inflight: deque = deque()   # dispatched, unfetched chunks
        self._fresh_slots: set = set()    # slots (re)filled since the
        #                                   last dispatch: their first
        #                                   token comes from the host
        # phase-time breakdown (bench: prefill / decode-stall / host)
        self.time_prefill_s = 0.0
        self.time_stall_s = 0.0
        self.time_host_s = 0.0
        self._zeros_seen_cache: Dict[int, jax.Array] = {}
        # per-rung measured chunk cost (seconds/chunk), built by warmup;
        # empty → _pick_chunk uses the zero-waste heuristic
        self._chunk_cost: Dict[int, float] = {}
        self._force_chunk: Optional[int] = None

        dec = self.dec

        def prefill(weights, k, v, ids, slots, last_idx, temp, key,
                    top_ks, top_ps, rep, seen):
            logits, k, v = dec._prefill_impl(weights, k, v, ids, slots,
                                             last_idx)
            tok = self._sample_rich(logits, temp, key, top_ks, top_ps,
                                    rep, seen)
            return tok, k, v

        def prefill_prefix(weights, k, v, ids, slots, last_idx,
                           n_cached, prefix_tables, temp, key, top_ks,
                           top_ps, rep, seen):
            logits, k, v = dec._prefill_prefix_impl(
                weights, k, v, ids, slots, last_idx, n_cached,
                prefix_tables)
            tok = self._sample_rich(logits, temp, key, top_ks, top_ps,
                                    rep, seen)
            return tok, k, v

        def decode_chunk(weights, k, v, first_ids, tables_all, ctx_all,
                         slots_all, temp, keys_all):
            """T decode steps as one lax.scan (one dispatch per chunk)."""
            def step(carry, xs):
                last_ids, kp, vp = carry
                tables, ctx, slots, key = xs
                logits, kp, vp = dec._decode_logits(
                    weights, kp, vp, last_ids, tables, ctx, slots)
                nxt = self._sample(logits, temp, key)
                return (nxt, kp, vp), nxt
            (_, k, v), toks = jax.lax.scan(
                step, (first_ids, k, v),
                (tables_all, ctx_all, slots_all, keys_all))
            return toks.swapaxes(0, 1), k, v   # [b, T]

        def decode_chunk_rich(weights, k, v, first_ids, tables_all,
                              ctx_all, slots_all, temp, keys_all,
                              top_ks, top_ps, rep, seen):
            """Per-request-sampling variant: the scan additionally
            carries the token-presence mask (repetition penalty) and
            applies per-slot top_k/top_p masks. Compiled only when a
            request actually asks for them."""
            def step(carry, xs):
                last_ids, kp, vp, seen_c = carry
                tables, ctx, slots, key = xs
                logits, kp, vp = dec._decode_logits(
                    weights, kp, vp, last_ids, tables, ctx, slots)
                nxt = self._sample_rich(logits, temp, key, top_ks,
                                        top_ps, rep, seen_c)
                seen_c = seen_c.at[
                    jnp.arange(seen_c.shape[0]), nxt].set(True)
                return (nxt, kp, vp, seen_c), nxt
            (_, k, v, _), toks = jax.lax.scan(
                step, (first_ids, k, v, seen),
                (tables_all, ctx_all, slots_all, keys_all))
            return toks.swapaxes(0, 1), k, v   # [b, T]

        def merge_first(toks_dev, last_idx, overrides, use_host):
            """First tokens of the next chunk from the previous chunk's
            device output (continuing slots) or host values (fresh
            slots) — keeps the chunk-to-chunk dependency on-device."""
            gathered = toks_dev[jnp.arange(toks_dev.shape[0]), last_idx]
            return jnp.where(use_host, overrides, gathered)

        self._prefill_j = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefill_prefix_j = jax.jit(prefill_prefix,
                                         donate_argnums=(1, 2))
        self._decode_j = jax.jit(decode_chunk, donate_argnums=(1, 2))
        self._decode_rich_j = jax.jit(decode_chunk_rich,
                                      donate_argnums=(1, 2))
        self._merge_first_j = jax.jit(merge_first)

    def _sample(self, logits, temp, key):
        """In-program sampling: per-slot temperature (<=0 → greedy),
        engine-static top_k."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if self.top_k > 0:
            kth = jax.lax.top_k(logits, self.top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -1e30, logits)
        t = jnp.maximum(temp, 1e-6)[:, None]
        sampled = jax.random.categorical(
            key, logits / t, axis=-1).astype(jnp.int32)
        return jnp.where(temp > 0.0, sampled, greedy)

    def _sample_rich(self, logits, temp, key, top_ks, top_ps, rep,
                     seen):
        """Per-request sampling, all mask-based so one compiled program
        serves every parameter combination (models/generation.py:26-46
        semantics): repetition penalty over the seen mask, per-slot
        top_k via the k-th order statistic of the sorted logits,
        per-slot top_p nucleus over the tempered distribution.
        logits [b, V] f32; temp/top_ps/rep [b] f32; top_ks [b] i32;
        seen [b, V] bool."""
        v = logits.shape[-1]
        logits = logits.astype(jnp.float32)
        # repetition penalty (HF semantics: shrink positive logits,
        # amplify negative ones, only for already-seen tokens)
        pen = jnp.where(logits > 0, logits / rep[:, None],
                        logits * rep[:, None])
        logits = jnp.where(seen & (rep != 1.0)[:, None], pen, logits)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lt = logits / jnp.maximum(temp, 1e-6)[:, None]
        # ONE descending sort serves both filters
        sorted_l = jnp.sort(lt, axis=-1)[..., ::-1]         # [b, V]
        # per-slot top_k: k-th largest value as the cutoff
        k_idx = jnp.clip(top_ks - 1, 0, v - 1)
        kth = jnp.take_along_axis(sorted_l, k_idx[:, None], axis=1)
        lt = jnp.where((top_ks > 0)[:, None] & (lt < kth), -1e30, lt)
        # per-slot top_p over the top_k-FILTERED distribution (the
        # generation.py order: top_k first, then nucleus). The filtered
        # sorted array is just the sorted prefix with ranks >= k masked,
        # so the single sort above still serves.
        rank = jnp.arange(v)[None, :]
        sorted_k = jnp.where(
            (top_ks > 0)[:, None] & (rank >= top_ks[:, None]),
            -1e30, sorted_l)
        probs = jax.nn.softmax(sorted_k, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff = cum - probs > top_ps[:, None]
        pth = jnp.where(cutoff, jnp.inf, sorted_k).min(
            axis=-1, keepdims=True)
        lt = jnp.where((top_ps < 1.0)[:, None] & (lt < pth), -1e30, lt)
        sampled = jax.random.categorical(key, lt, axis=-1) \
            .astype(jnp.int32)
        return jnp.where(temp > 0.0, sampled, greedy)

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    # -- public API ----------------------------------------------------------
    def add_request(self, prompt, sampling: Optional[SamplingParams] = None
                    ) -> int:
        """Queue a prompt ([len] ids; list/np/Tensor). Returns req_id."""
        if isinstance(prompt, Tensor):
            prompt = np.asarray(prompt._value)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        sp = sampling or SamplingParams()
        _bucket_for(int(prompt.size), self.buckets)  # validates length
        cache = self.dec.cache
        need = -(-(int(prompt.size) + sp.max_new_tokens)
                 // cache.block_size)
        if need > cache.num_blocks - 1:  # -1: scratch page
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{cache.num_blocks - 1}; shrink max_new_tokens/prompt "
                "or grow num_blocks")
        rid = next(self._ids)
        req = Request(rid, prompt, sp, t_submit=time.perf_counter())
        self._queue.append(req)
        return rid

    def result(self, req_id: int) -> np.ndarray:
        """Generated tokens (prompt excluded) of a finished request."""
        req = self._done[req_id]
        return np.asarray(req.out_tokens, np.int32)

    def request(self, req_id: int) -> Request:
        return self._done[req_id]

    @property
    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._inflight)
                or any(r is not None for r in self._slots))

    # -- scheduler -----------------------------------------------------------
    def _required_blocks(self, req: Request) -> int:
        total = req.prompt.size + req.sampling.max_new_tokens
        return -(-total // self.dec.cache.block_size)

    def _admit(self):
        """Fill free batch slots from the queue. Admission is
        capacity-aware (a request enters only if its whole worst-case
        page demand fits — net of prefix-cache reuse — so a running
        request can never hit pool exhaustion mid-decode) and BATCHED:
        admissible requests sharing a (wave, bucket) prefill in one
        dispatch (padded to a power-of-two group size to bound compile
        variants) — a burst of K arrivals costs ~1 prefill instead of K.

        Prefix caching buckets on SUFFIX length and splices matched
        blocks at allocation time. A matched block may be written by a
        prefill admitted in this same wave (its hashes register at
        allocation, before the write is dispatched): such a dependent
        request is assigned a LATER wave, and waves dispatch in order —
        on-device program order then guarantees the reader sees the
        writer's pages. Requests in one dispatch never read each
        other's blocks (same-wave ⇒ no pending-block dependency)."""
        cache = self.dec.cache
        free_slots = [si for si in range(self.max_b)
                      if self._slots[si] is None]
        admitted = []              # (slot, req, bucket, n_cached, wave)
        pending_wave: Dict[int, int] = {}   # block → wave writing it
        for si in free_slots:
            if not self._queue:
                break
            req = self._queue[0]
            total = int(req.prompt.size) + req.sampling.max_new_tokens
            if self.prefix_caching:
                try:
                    # one hash walk: the capacity check happens inside
                    # allocate_with_prefix BEFORE any mutation, so a
                    # refusal leaves the pool untouched
                    reused, n_cached = cache.allocate_with_prefix(
                        req.req_id, req.prompt, total)
                except RuntimeError:
                    break  # head-of-line: keep FIFO, wait for frees
                self._queue.popleft()
                wave = 1 + max((pending_wave.get(b, -1)
                                for b in reused), default=-1)
                table = cache.seq_blocks(req.req_id)
                n_full = int(req.prompt.size) // cache.block_size
                for b in table[len(reused):n_full]:
                    pending_wave[b] = wave
                bucket = _bucket_for(int(req.prompt.size) - n_cached,
                                     self.buckets)
            else:
                if cache.free_blocks < self._required_blocks(req):
                    break
                self._queue.popleft()
                cache.allocate(req.req_id, total)
                n_cached, wave = 0, 0
                bucket = _bucket_for(int(req.prompt.size), self.buckets)
            admitted.append((si, req, bucket, n_cached, wave))
        by_group: dict = {}
        for si, req, bucket, n_cached, wave in admitted:
            by_group.setdefault((wave, bucket), []).append(
                (si, req, n_cached))
        # dispatch EVERY admission prefill before fetching ANY result
        # (waves ascending — see above): through the remote tunnel a
        # blocking fetch costs a full round trip (~75 ms), so a
        # 16-request burst over 4 groups paid 4 RTTs; one batched
        # device_get pays it once while the chunks pipeline on the
        # device (measured r5: capacity-row prefill wall 0.47 s ->
        # ~0.15 s for 17.6 ms of device work)
        pending = []
        for wave, bucket in sorted(by_group):
            group = by_group[(wave, bucket)]
            if len(group) > 1:
                w = min(self.PREFILL_GROUP, self.max_b)
                for i in range(0, len(group), w):
                    pending.append(
                        self._prefill_dispatch(bucket, group[i:i + w], w))
            else:
                pending.append(self._prefill_dispatch(bucket, group, 1))
        if pending:
            t0 = time.perf_counter()
            fetched = jax.device_get([t for t, _ in pending])
            for (_, group), toks in zip(pending, fetched):
                self._prefill_complete(np.asarray(toks), group)
            self.time_prefill_s += time.perf_counter() - t0

    # prefill dispatch widths: exactly TWO compile variants per bucket
    # (a variant per group size would compile-storm on bursty arrivals —
    # measured 4x throughput loss through the remote-compile tunnel)
    PREFILL_GROUP = 4

    def _prefill_dispatch(self, bucket: int, group, gp: int):
        """Dispatch one prefill group. `group` rows are
        (slot, req, n_cached): with prefix caching every row prefills
        only its uncovered suffix — `bucket` is a SUFFIX bucket, RoPE
        positions/slot mappings start at n_cached, and the row's cached
        pages ride along as a scratch-padded prefix table."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        vocab = self.dec.cfg.vocab_size
        ids = np.zeros((gp, bucket), np.int32)
        slots = np.full((gp, bucket), self._scratch_slot, np.int32)
        last_idx = np.zeros(gp, np.int32)
        ncv = np.zeros(gp, np.int32)
        ptab = np.full((gp, self._prefix_pages), self._scratch_block,
                       np.int32)
        temps = np.zeros(gp, np.float32)
        top_ks = np.zeros(gp, np.int32)
        top_ps = np.ones(gp, np.float32)
        reps = np.ones(gp, np.float32)
        any_rep = any(req.sampling.repetition_penalty != 1.0
                      for _, req, _ in group)
        seen = np.zeros((gp, vocab), bool) if any_rep else None
        for row, (si, req, n_cached) in enumerate(group):
            s = int(req.prompt.size) - n_cached
            ids[row, :s] = req.prompt[n_cached:]
            slots[row, :s] = [cache.extend(req.req_id)
                              for _ in range(s)]
            last_idx[row] = s - 1
            ncv[row] = n_cached
            if n_cached:
                pb = cache.seq_blocks(req.req_id)[
                    :n_cached // cache.block_size]
                ptab[row, :len(pb)] = pb
            sp = req.sampling
            temps[row] = sp.temperature
            # engine-level top_k is the default where the request does
            # not set its own (None); an explicit 0 disables it
            top_ks[row] = self.top_k if sp.top_k is None else sp.top_k
            top_ps[row] = sp.top_p
            reps[row] = sp.repetition_penalty
            if sp.repetition_penalty != 1.0:
                seen[row, req.prompt] = True   # FULL prompt, cached too
        seen_dev = jnp.asarray(seen) if any_rep \
            else self._zeros_seen(gp, vocab)
        # the suffix-prefix program pays a per-layer page gather plus
        # dense attention over the (possibly all-masked) prefix columns:
        # only groups with at least one actual hit take it — all-miss
        # groups keep the plain flash prefill, so disjoint traffic is
        # unchanged by enabling the cache
        if any(n for _, _, n in group):
            toks, cache.k, cache.v = self._prefill_prefix_j(
                self.dec.weights, cache.k, cache.v, jnp.asarray(ids),
                jnp.asarray(slots), jnp.asarray(last_idx),
                jnp.asarray(ncv), jnp.asarray(ptab),
                jnp.asarray(temps), self._next_key(),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(reps), seen_dev)
        else:
            toks, cache.k, cache.v = self._prefill_j(
                self.dec.weights, cache.k, cache.v, jnp.asarray(ids),
                jnp.asarray(slots), jnp.asarray(last_idx),
                jnp.asarray(temps), self._next_key(),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(reps), seen_dev)
        self.time_prefill_s += time.perf_counter() - t0
        return toks, group

    def _prefill_complete(self, toks: np.ndarray, group):
        """Post-fetch bookkeeping for one dispatched prefill chunk."""
        now = time.perf_counter()
        for row, (si, req, _) in enumerate(group):
            tok = int(toks[row])
            req.state = "running"
            req.t_first_token = now
            req.out_tokens.append(tok)
            req.planned = 1
            self.generated_tokens += 1
            self._slots[si] = req
            self._last_tok[si] = tok
            self._fresh_slots.add(si)
            if self._is_finished(req):
                self._retire(si)

    def _is_finished(self, req: Request) -> bool:
        sp = req.sampling
        return (len(req.out_tokens) >= sp.max_new_tokens
                or (sp.eos_token_id is not None
                    and req.out_tokens[-1] == sp.eos_token_id))

    def _retire(self, si: int):
        req = self._slots[si]
        req.state = "done"
        req.t_done = time.perf_counter()
        self._done[req.req_id] = req
        self._slots[si] = None
        if self._inflight:
            # an in-flight chunk still reads/writes this request's pages
            # (it was dispatched assuming continuation): free them only
            # after the LAST dispatched chunk is fetched
            self._inflight[-1]["free_after"].append(req.req_id)
        else:
            self.dec.cache.free(req.req_id)

    def _zeros_seen(self, rows: int, vocab: int):
        """Cached device-resident all-False seen mask (per row count)."""
        cached = self._zeros_seen_cache.get(rows)
        if cached is None:
            cached = jnp.zeros((rows, vocab), bool)
            self._zeros_seen_cache[rows] = cached
        return cached

    def _warmup_prompt(self, n: int) -> np.ndarray:
        """Throwaway warmup prompt with a per-call token fill: two
        warmup prompts must never share a block-aligned prefix, or the
        prefix cache would splice them together and the full-length
        (bucket, width) prefill programs warmup exists to compile would
        never run."""
        self._warmup_fill = getattr(self, "_warmup_fill", 0) + 1
        v = 1 + self._warmup_fill % max(1, self.dec.cfg.vocab_size - 1)
        return np.full(n, v, np.int32)

    def _rep_active(self) -> bool:
        return any(r is not None and
                   r.sampling.repetition_penalty != 1.0
                   for r in self._slots)

    def _pick_chunk(self, active) -> int:
        """Pick the ladder rung for this chunk.

        With a measured per-rung cost table (built by warmup): maximize
        delivered tokens per second — tokens(c) = sum over active slots
        of min(c, remaining budget); cost(c) was measured on THIS
        device/link. Overshooting a slot's budget (it idles on the
        scratch page for the tail) is chosen exactly when the per-chunk
        overhead (e.g. host↔device round trip) outweighs the wasted
        steps — a property of the deployment, not a constant.

        Without the table (warmup not run): zero-waste heuristic —
        largest rung every budget covers when idle; when requests are
        queued, largest rung the SOONEST-draining slot covers (so its
        slot frees promptly). Either way, queue pressure with EOS-able
        requests pins the smallest rung: such a slot may free any step.
        """
        if len(self.chunks) == 1:
            return self.chunks[0]
        if self._queue and any(
                self._slots[si].sampling.eos_token_id is not None
                for si in active):
            return self.chunks[0]
        lefts = [self._slots[si].sampling.max_new_tokens
                 - self._slots[si].planned for si in active]
        if self._chunk_cost:
            best, best_rate = self.chunks[0], -1.0
            for c in self.chunks:
                cost = self._chunk_cost.get(c)
                if cost is None:
                    continue
                tokens = sum(min(c, max(0, lf)) for lf in lefts)
                rate = tokens / cost
                if rate > best_rate + 1e-9:
                    best, best_rate = c, rate
            return best
        bound = min(lefts) if self._queue else max(lefts)
        best = self.chunks[0]
        for c in self.chunks[1:]:
            if c <= bound:
                best = c
        return best

    def _dispatch_chunk(self) -> bool:
        """Dispatch ONE decode chunk for the current active slots
        without waiting for the previous chunk: first tokens of
        continuing slots are gathered from the in-flight chunk's DEVICE
        output (no host round trip); freshly admitted slots take their
        prefill token from the host."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        active = [si for si in range(self.max_b)
                  if self._slots[si] is not None]
        if not active:
            self.time_host_s += time.perf_counter() - t0
            return False
        T = self._force_chunk or self._pick_chunk(active)
        mb, mp = self.max_b, self.dec.max_pages
        # host-precomputed page schedule: slots past their token budget
        # (or inactive) aim at the scratch page for the rest of the chunk
        tables = np.full((T, mb, mp), self._scratch_block, np.int32)
        ctx = np.zeros((T, mb), np.int32)
        slots = np.full((T, mb), self._scratch_slot, np.int32)
        temps = np.zeros(mb, np.float32)
        top_ks = np.zeros(mb, np.int32)
        top_ps = np.ones(mb, np.float32)
        reps = np.ones(mb, np.float32)
        vocab = self.dec.cfg.vocab_size
        rich = False
        steps_of: Dict[int, int] = {}
        reqs_of: Dict[int, Request] = {}
        for si in active:
            req = self._slots[si]
            sp = req.sampling
            temps[si] = sp.temperature
            top_ks[si] = self.top_k if sp.top_k is None else sp.top_k
            top_ps[si] = sp.top_p
            reps[si] = sp.repetition_penalty
            rich = rich or sp.needs_rich_sampling
            # budget at DISPATCH time: tokens planned (dispatched), not
            # tokens fetched — EOS cuts are discovered at collection
            steps = max(0, min(T, sp.max_new_tokens - req.planned))
            req.planned += steps
            steps_of[si] = steps
            reqs_of[si] = req
            for t in range(steps):
                ctx[t, si] = cache.context_len(req.req_id)
                slots[t, si] = cache.extend(req.req_id)
            # one table per slot per chunk: after the extends above the
            # block list is final for the whole chunk, and entries past
            # a step's context length are masked by ctx anyway
            tables[:, si, :] = cache.block_table(req.req_id, mp)[None]
        if all(s == 0 for s in steps_of.values()):
            # every active slot is budget-drained and just awaiting
            # collection — nothing to run
            self.time_host_s += time.perf_counter() - t0
            return False

        # first tokens: device gather from the newest in-flight chunk
        # for continuing slots, host values for fresh/0-step slots
        if self._inflight:
            prev = self._inflight[-1]
            last_idx = np.zeros(mb, np.int32)
            override = np.asarray(self._last_tok, np.int32).copy()
            use_host = np.ones(mb, bool)
            for si in active:
                psteps = prev["steps"].get(si, 0)
                if (psteps > 0 and si not in self._fresh_slots
                        and prev["reqs"].get(si) is reqs_of[si]):
                    use_host[si] = False
                    last_idx[si] = psteps - 1
            first_ids = self._merge_first_j(
                prev["toks"], jnp.asarray(last_idx),
                jnp.asarray(override), jnp.asarray(use_host))
        else:
            first_ids = jnp.asarray(self._last_tok)
        self._fresh_slots.clear()

        keys = jax.random.split(self._next_key(), T)
        if rich:
            if any(reqs_of[si].sampling.repetition_penalty != 1.0
                   for si in active):
                seen = np.zeros((mb, vocab), bool)
                for si in active:
                    req = reqs_of[si]
                    if req.sampling.repetition_penalty != 1.0:
                        seen[si, req.prompt] = True
                        if req.out_tokens:
                            seen[si, np.asarray(req.out_tokens)] = True
                seen_dev = jnp.asarray(seen)
            else:
                # top_k/top_p-only chunk: the mask is multiplied by
                # (rep != 1) == False in-program — reuse a cached
                # device-resident zeros mask instead of shipping
                # [mb, vocab] bools through the tunnel every chunk
                seen_dev = self._zeros_seen(mb, vocab)
            toks, cache.k, cache.v = self._decode_rich_j(
                self.dec.weights, cache.k, cache.v, first_ids,
                jnp.asarray(tables), jnp.asarray(ctx),
                jnp.asarray(slots), jnp.asarray(temps), keys,
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(reps), seen_dev)
        else:
            toks, cache.k, cache.v = self._decode_j(
                self.dec.weights, cache.k, cache.v, first_ids,
                jnp.asarray(tables), jnp.asarray(ctx),
                jnp.asarray(slots), jnp.asarray(temps), keys)
        self._inflight.append({"toks": toks, "steps": steps_of,
                               "reqs": reqs_of, "T": T,
                               "free_after": []})
        self.time_host_s += time.perf_counter() - t0
        return True

    def _collect_oldest(self):
        """Fetch and process the oldest in-flight chunk (the only
        host-blocking point of the decode path)."""
        ch = self._inflight.popleft()
        t0 = time.perf_counter()
        toks = np.asarray(ch["toks"])              # [mb, T] — blocks
        self.time_stall_s += time.perf_counter() - t0
        self.decode_steps += ch["T"]
        for si, steps in ch["steps"].items():
            req = ch["reqs"][si]
            if req.state != "running":
                continue       # retired while this chunk was in flight
            for t in range(steps):
                tok = int(toks[si, t])
                req.out_tokens.append(tok)
                self.generated_tokens += 1
                self._last_tok[si] = tok
                if self._is_finished(req):
                    break      # mid-chunk EOS: discard the tail
            if self._is_finished(req) and self._slots[si] is req:
                self._retire(si)
        for rid in ch["free_after"]:
            self.dec.cache.free(rid)

    def step(self) -> bool:
        """One engine iteration: admit, dispatch the next decode chunk,
        then collect down to the pipeline depth (1 chunk stays in
        flight in overlap mode, so host admission/bookkeeping runs
        while the device decodes). Returns True while there is still
        work."""
        self._admit()
        dispatched = self._dispatch_chunk()
        depth = 1 if (dispatched and self.overlap
                      and not self._rep_active()) else 0
        while len(self._inflight) > depth:
            self._collect_oldest()
        if self._debug_pool:
            # PADDLE_TPU_POOL_DEBUG=1: assert the pool invariant
            # (free + cached + referenced == num_blocks, refs == table
            # contents) after every scheduler step
            self.dec.cache.debug_check()
        return self.has_work

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {req_id: generated tokens}."""
        while self.step():
            pass
        return {rid: self.result(rid) for rid in list(self._done)}

    def warmup(self, prompt_len: Optional[int] = None):
        """Pre-compile the serving programs — BOTH prefill widths for
        every bucket (or just prompt_len's bucket when given), the
        prefix-cache HIT prefill for every hit-reachable suffix bucket,
        plus the decode chunk — with throwaway requests, so no user
        request pays a compile. Worth calling once at deployment;
        finished-request stats AND the prefix cache are cleared
        afterwards. Warns if the KV pool is too small to exercise the
        burst width (that variant would then compile on the first real
        burst)."""
        import warnings as _warnings
        plens = ([prompt_len] if prompt_len is not None
                 else list(self.buckets))
        cache = self.dec.cache
        width = min(self.PREFILL_GROUP, self.max_b)
        if self.max_b < 2:
            _warnings.warn(
                "warmup: max_batch_size < 2 — the burst prefill path "
                "never runs on this engine; only width-1 is warmed")
        for plen in plens:
            # phase 1: a single request — the width-1 program
            self.add_request(self._warmup_prompt(plen),
                             SamplingParams(max_new_tokens=2))
            self.run_to_completion()
            if self.max_b < 2:
                continue
            # phase 2: a burst — the width-`width` program. The burst
            # path only runs if >= 2 requests admit TOGETHER.
            need = 2 * -(-(plen + 2) // cache.block_size)
            if cache.available_blocks < need:
                _warnings.warn(
                    f"warmup: pool too small to exercise the width-"
                    f"{width} prefill at bucket {plen} (need {need} "
                    "free pages); the first real burst there will pay "
                    "that compile")
                continue
            for _ in range(width):
                self.add_request(self._warmup_prompt(plen),
                                 SamplingParams(max_new_tokens=2))
            self.run_to_completion()
        # prefix-cache HIT programs: the suffix-prefix prefill compiles
        # per (suffix bucket, width), and warmup's distinct-fill miss
        # traffic never runs it — seed a one-block prefix, then admit
        # hits whose suffix lands in each reachable bucket (width 1),
        # plus one burst at the first reachable bucket (width `width`)
        if self.prefix_caching:
            bs = cache.block_size
            prefix = self._warmup_prompt(bs)
            seeded = burst_done = False

            def _hit_round(s_suf, rows):
                for _ in range(rows):
                    self.add_request(
                        np.concatenate([prefix,
                                        self._warmup_prompt(s_suf)]),
                        SamplingParams(max_new_tokens=2))
                self.run_to_completion()

            for b in self.buckets:
                s_suf = min(b, self.buckets[-1] - bs)
                if s_suf <= 0 or _bucket_for(s_suf, self.buckets) != b:
                    continue   # no runtime hit can land in this bucket
                per_hit = -(-(bs + s_suf + 2) // bs)
                if cache.available_blocks < per_hit + 1:
                    _warnings.warn(
                        f"warmup: pool too small to warm the prefix-hit "
                        f"prefill at suffix bucket {b}; the first real "
                        "hit there will pay that compile")
                    continue
                if not seeded:
                    # park the shared prefix block (suffix of 1 token)
                    self.add_request(
                        np.concatenate([prefix, self._warmup_prompt(1)]),
                        SamplingParams(max_new_tokens=1))
                    self.run_to_completion()
                    seeded = True
                _hit_round(s_suf, 1)
                if not burst_done and self.max_b >= 2 and \
                        cache.available_blocks >= width * per_hit:
                    _hit_round(s_suf, width)
                    burst_done = True
        # rich-sampling + plain decode programs, once per ladder chunk
        # size (each T is its own compiled program): top_k=1 is greedy,
        # so the rich throwaway is deterministic but routes through
        # _decode_rich_j. Spanning MULTIPLE decode chunks also compiles
        # the overlap-mode _merge_first_j chunk-to-chunk gather.
        warmed_rungs = set()
        for c in self.chunks:
            if -(-(plens[0] + c + 2) // cache.block_size) > \
                    cache.available_blocks:
                _warnings.warn(
                    f"warmup: pool too small to warm chunk rung {c}; "
                    f"its first real dispatch will pay the compile")
                continue
            warmed_rungs.add(c)
            # pin the rung: the heuristic could skip a middle rung whose
            # budget lands on a bigger one (its compile would then leak
            # into the timed cost loop below)
            self._force_chunk = c
            try:
                self.add_request(self._warmup_prompt(plens[0]),
                                 SamplingParams(max_new_tokens=c + 2,
                                                temperature=1.0,
                                                top_k=1))
                self.run_to_completion()
                self.add_request(self._warmup_prompt(plens[0]),
                                 SamplingParams(max_new_tokens=c + 2))
                self.run_to_completion()
            finally:
                self._force_chunk = None
        # measure each rung's steady chunk cost (compiles are done):
        # one request pinned to rung c for 3 chunks; the stall+host
        # delta over 3 chunks is the per-chunk cost _pick_chunk's
        # tokens/cost policy uses
        if len(self.chunks) > 1:
            for c in self.chunks:
                if c not in warmed_rungs:
                    # never time an un-warmed rung: the measurement
                    # would absorb its XLA compile and the rate policy
                    # would shun the rung forever
                    continue
                # clamp the measurement to the pool: a production pool
                # sized for small budgets must not fail warmup. Prefer
                # 3 chunks; fall back to fewer; skip the rung (leaving
                # it out of the cost table) if even one doesn't fit.
                n_chunks = 3
                while n_chunks > 0:
                    need = -(-(plens[0] + n_chunks * c)
                             // cache.block_size)
                    if need <= cache.available_blocks:
                        break
                    n_chunks -= 1
                if n_chunks == 0:
                    _warnings.warn(
                        f"warmup: pool too small to measure chunk rung "
                        f"{c} (needs {-(-(plens[0] + c) // cache.block_size)} "
                        f"free pages); rung left uncosted — the rate "
                        f"policy will not select it")
                    continue
                self._force_chunk = c
                try:
                    before = self.time_stall_s + self.time_host_s
                    self.add_request(
                        self._warmup_prompt(plens[0]),
                        SamplingParams(max_new_tokens=n_chunks * c))
                    self.run_to_completion()
                    delta = (self.time_stall_s + self.time_host_s
                             - before)
                finally:
                    self._force_chunk = None
                self._chunk_cost[c] = max(delta / n_chunks, 1e-6)
        # warmup traffic must leave no trace: parked throwaway blocks
        # would otherwise occupy LRU slots (and could in principle be
        # spliced by a real request with the same fill pattern)
        cache.clear_prefix_cache()
        self.clear_finished()

    def clear_finished(self):
        """Drop finished requests + counters (e.g. after warmup) so
        stats() reflect only the workload that follows — including the
        prefix-cache hit/eviction counters, so warmup traffic cannot
        pollute the reported hit rate."""
        self._done.clear()
        self.decode_steps = 0
        self.generated_tokens = 0
        self.time_prefill_s = 0.0
        self.time_stall_s = 0.0
        self.time_host_s = 0.0
        self.dec.cache.reset_prefix_stats()

    def stats(self) -> dict:
        """Latency/throughput summary over finished requests."""
        cache = self.dec.cache
        lats = [r.latency_s for r in self._done.values()
                if r.latency_s is not None]
        ttfts = [r.ttft_s for r in self._done.values()
                 if r.ttft_s is not None]

        def pct(xs, p):
            # Interpolated (the truncating index form overstated
            # p50/p99 on small samples).
            return float(np.quantile(xs, p)) if xs else None

        return {
            "finished": len(self._done),
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "latency_p50_s": pct(lats, 0.50),
            "latency_p99_s": pct(lats, 0.99),
            "ttft_p50_s": pct(ttfts, 0.50),
            "ttft_p99_s": pct(ttfts, 0.99),
            # where the wall time went (bench breakdown): wall time of
            # the engine's blocking call sites. CAVEAT under overlap:
            # the device runs one queue, so a prefill fetch issued
            # while a decode chunk is in flight also waits for that
            # chunk — time_prefill_s then absorbs in-flight decode
            # time and time_decode_stall_s undercounts it. The split
            # is exact with overlap=False; with overlap it bounds
            # host-side attribution (time_host_s) exactly and the
            # device phases jointly.
            "time_prefill_s": self.time_prefill_s,
            "time_decode_stall_s": self.time_stall_s,
            "time_host_s": self.time_host_s,
            # prefix cache: hit tokens = prompt tokens whose KV was
            # spliced from cached blocks instead of re-prefilled;
            # hit rate is over all prompt tokens seen at admission
            "prefix_cache_hit_tokens": cache.prefix_hit_tokens,
            "prefix_cache_hit_rate": (
                cache.prefix_hit_tokens / cache.prefix_query_tokens
                if cache.prefix_query_tokens else 0.0),
            "prefix_cache_evictions": cache.prefix_evictions,
            "free_blocks": cache.free_blocks,
            "cached_blocks": cache.cached_blocks,
        }
