"""Continuous-batching LLM serving engine over the paged KV pool.

Reference: the AnalysisPredictor serving subsystem
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h:100)
plus the block_multihead_attention continuous-decode path
(/root/reference/python/paddle/incubate/nn/functional/
block_multihead_attention.py). The reference composes CUDA kernels under
a pass-optimized executor; the TPU-native equivalent is a *fixed-shape*
scheduler: XLA programs cannot change batch size per step, so continuous
batching becomes a fixed grid of batch slots with per-slot activity —
the same trick the paged pool already plays for sequence length.

Architecture (all shapes static; compiled programs: ONE decode chunk
per ladder rung, TWO prefill widths per active prompt bucket, plus two
width-1 no-sample chunk programs when chunked prefill is on):
- admission: queued requests claim free batch slots (capacity-aware,
  FIFO) and enter the "prefilling" state. Admission only allocates —
  it never dispatches or blocks on the device.
- chunked prefill (Sarathi-style; prefill_chunk=256 by default): a
  prompt suffix longer than one chunk is split into fixed-size chunks;
  chunk i prefills at position offset i*C with chunks 0..i-1's pages
  riding along as a prefix table — exactly the prefix-cache-hit
  machinery, so one compiled (C, width-1) program serves every chunk
  of every prompt. Intermediate chunks sample nothing (no last-token
  logits; the no-sample programs consume no PRNG key); only the FINAL
  chunk takes the first-token logits. The scheduler interleaves
  prefill chunks with decode chunks under a per-step token budget
  (prefill_budget, default one chunk), so a long prompt arriving
  mid-stream delays running decodes by at most ~one chunk of prefill
  per decode chunk instead of the whole prompt — the ITL cliff the
  monolithic path had. Prefill dispatches join the SAME in-flight
  queue as decode chunks; their results are fetched at collection
  time, never inside admission.
- automatic prefix caching (prefix_caching=True, the default): on
  admission the prompt is hashed at block granularity against the
  pool's chain-hash index (PagedKVCache.match_prefix); matched full
  blocks are spliced into the request's block table (ref++, no copy)
  and ONLY the uncovered suffix prefills — bucketed on SUFFIX length,
  RoPE positions and slot mappings offset by n_cached, attention run
  over [gathered prefix pages ++ suffix] (the decoder's
  _prefill_prefix_impl; n_cached is data, so one compiled program per
  (bucket, width) serves every hit length). The worst-case admission
  capacity check credits reusable blocks, so cache hits raise
  effective pool capacity. A request may splice blocks that another
  still-prefilling request has yet to write (they register in the
  hash index at allocation): the reader records a dependency on the
  writer's dispatch progress and its own chunks hold back until the
  writer's covering chunk has been dispatched — device program order
  then makes the write visible to the read. Retired requests return
  blocks through the ref-counted path: full hashed blocks park in the
  pool's LRU for future splices and are evicted only when the free
  list runs dry.
- decode: ONE program serves every step — a lax.scan over a
  chunk_size-token schedule (the page/slot schedule is deterministic, so
  the host precomputes it), [max_batch] wide, inactive / finished /
  still-prefilling slots aimed at the scratch page and their outputs
  discarded. Sampling (per-slot temperature, engine-static top_k)
  happens in-program, so only [max_batch, chunk] token ids cross the
  host boundary per chunk. Chunking is what makes continuous batching
  viable on TPU: per-dispatch round-trips (hundreds of ms through a
  remote-compile tunnel, ~10us locally) amortize over chunk_size
  tokens, while admission still happens every chunk boundary.
- completion: EOS/max-token slots free their pages (mid-chunk EOS trims
  the tail tokens); the slot admits the next queued request at the next
  chunk boundary.

Weight-only int8 (weight_dtype="int8") stores matmul weights as
per-channel int8 + scale — decode is HBM-bandwidth-bound, so halving
weight bytes is the serving-side quantization that actually pays on TPU.

Fault tolerance (ISSUE 4 — the runtime analogue of flightcheck):
failures are absorbed at REQUEST granularity; step() never raises on a
per-request fault and the pool invariant holds through every recovery.
- deadlines/cancel: SamplingParams.deadline_s + cancel(req_id) move a
  request to a terminal ABORTED state from any live stage, unwinding
  splice-pending hash registrations, restarting dependent readers and
  freeing pages only once no in-flight chunk references them.
- preemption-with-recompute: admission="optimistic" oversubscribes the
  pool (prefill pages only); KV pressure preempts the newest/lowest-
  priority running request, whose generated history re-prefills through
  the NO-SAMPLE chunk programs (no PRNG key drawn — the engine key
  stream is untouched, so greedy outputs are token-identical) riding
  the prefix cache for near-zero recompute on hits. Epoch guards drop a
  preempted life's in-flight tokens at collection.
- bounded retry: every dispatch/fetch goes through _device_call —
  exponential-backoff retries re-issue the SAME call (same key), then
  fail the involved requests with a structured Request.error.
- overload shedding: add_request raises EngineOverloaded on the queue
  cap or when backlog/rate math says a deadline cannot be met.
- chaos: utils/chaos.ChaosMonkey injects seeded allocator OOMs,
  dispatch/collect faults and latency spikes at the sanctioned hooks;
  tools/chaos_serving.py gates token-identity under fault schedules.

Speculative decoding (ISSUE 9; spec_decode=SpecConfig(...)): a host
drafter (n-gram/prompt-lookup by default; any Drafter plugs in)
proposes k continuation tokens per greedy decode column, which ride as
EXTRA ROWS of the ragged program — carried token at position ctx,
drafts at ctx+1..ctx+k, each with row_ctx = position + 1, the exact
visibility contract prefill-chunk rows already use. One forward gives
the teacher's token at every position; the decoder's _spec_accept
computes the longest-accepted-prefix IN-program and neutralizes
rejected rows' pool writes via the scratch slot; the host delivers
1..k+1 tokens per column per dispatch and rolls the allocator back
past them (PagedKVCache.rollback). Greedy outputs are bit-identical to
spec-off — every emitted token is the teacher's own argmax under a
verified prefix. Verify chunks are synchronous (acceptance decides the
next schedule); draft rows compete with prefill chunks under the
per-step row budget; rich-sampling columns pause drafting. All PR-4
invariants hold with drafts in flight: a mid-window preemption blanks
the victim's rows through the staleness sweep, epoch guards drop a
previous life's verify results, and dispatch/collect retries re-issue
the same program.

Fleet serving (ISSUE 11): ONE engine is ONE failure domain. R engines
compose into a dp x tp fleet behind inference/fleet.py::Router —
prefix-affinity routing over each replica's chain-hash index, a
per-replica circuit breaker fed by this engine's dispatch_exhaustions
counter, and drain-and-migrate failover riding adopt_request (the
preemption-recompute machinery pointed across engines: history
re-prefills through the no-sample chunk programs, so greedy outputs
are token-identical across the migration). The engine itself stays
fleet-agnostic; devices= is the only constructor surface the Router
needs (a disjoint device slice per tp-sharded replica).
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops.paged_attention import KVCacheExhausted
from ..utils.telemetry import (CompileWatch, Reservoir, SLOMonitor,
                               SLOPolicy)
from .paged_decode import PagedLlamaDecoder
from .spec_decode import SpecConfig

__all__ = ["EngineOverloaded", "SamplingParams", "Request",
           "ServingEngine", "SpecConfig"]


class EngineOverloaded(RuntimeError):
    """Typed admission rejection (overload shedding): the queue-depth x
    deadline estimate says the request cannot meet its deadline, or the
    hard queue-depth cap is hit. Raised by add_request BEFORE the
    request is queued, so the caller can retry elsewhere / later —
    rejecting at admission is cheaper than burning pool capacity on a
    request that will be dead on arrival."""


class _DispatchFailed(Exception):
    """Internal: a device dispatch/fetch exhausted its retry budget.
    Carries the site kind and the last underlying exception; converted
    by the call site into structured per-request failures (the engine
    itself never dies on a dispatch error)."""

    def __init__(self, kind: str, cause: BaseException):
        super().__init__(f"{kind}: {cause!r}")
        self.kind = kind
        self.cause = cause


@dataclass
class SamplingParams:
    """Per-request sampling controls (reference generation surface:
    /root/reference/python/paddle/nn/decode.py:994 dynamic_decode +
    the incubate serving path). temperature<=0 means greedy; top_k=None
    defers to the engine-level top_k default while top_k=0 explicitly
    disables the filter (even against an engine default); top_p=1.0
    and repetition_penalty=1.0 are off. All are PER REQUEST and applied
    in-program (mask-based — no new compile variants per value)."""
    temperature: float = 0.0
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    top_k: Optional[int] = None
    top_p: float = 1.0
    repetition_penalty: float = 1.0
    # -- fault-tolerance surface ------------------------------------------
    # deadline_s: wall-clock budget from submit; a request past it is
    # ABORTED (partial tokens kept, deadline_misses counted) and — when
    # the engine can already tell at admission that the deadline cannot
    # be met — shed with EngineOverloaded instead of queued.
    deadline_s: Optional[float] = None
    # priority: higher survives longer under KV pressure (preemption
    # victims are picked lowest-priority-first, newest-first on ties)
    priority: int = 0
    # -- multi-tenant surface (ISSUE 10) ----------------------------------
    # adapter_id: serve this request through a LoRA adapter registered
    # in the engine's AdapterRegistry (None = the base model). The
    # adapter is faulted into the shared block pool at admission and
    # its per-row deltas ride the ragged step program; prefix-cache
    # hashes are salted with the id so splices never cross tenants.
    adapter_id: Optional[object] = None
    # allowed_tokens: vocab restriction applied IN-PROGRAM to this
    # request's decode columns before sampling (the minimal structured
    # decoding hook — "own output schema"; grammar FSMs are future
    # work). Either a boolean mask of length vocab_size or a sequence
    # of allowed token ids; greedy becomes constrained greedy (argmax
    # over the masked logits) and sampling renormalizes over the mask.
    allowed_tokens: Optional[object] = None

    @property
    def needs_rich_sampling(self) -> bool:
        # an EXPLICIT top_k (including 0, which must be able to override
        # an engine-level default) routes through the per-request path;
        # a vocab mask rides the same mask-based program family
        return (self.top_k is not None or self.top_p < 1.0
                or self.repetition_penalty != 1.0
                or self.allowed_tokens is not None)


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray                    # [prompt_len] int32
    sampling: SamplingParams
    out_tokens: List[int] = field(default_factory=list)
    t_submit: float = 0.0
    t_admit: Optional[float] = None       # slot claimed (queue wait ends)
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    # queued | prefilling | running | done, plus the terminal fault
    # states: aborted (cancel/deadline — partial tokens kept) and
    # failed (dispatch error after retries — structured `error` set)
    state: str = "queued"
    error: Optional[str] = None   # why the request aborted/failed
    # tokens DISPATCHED (prefill + scheduled decode steps) — may exceed
    # len(out_tokens) while a chunk is in flight or after an EOS cut
    planned: int = 0
    # -- preemption-with-recompute ----------------------------------------
    # resume: the request was preempted while RUNNING; on re-admission
    # its prefill source is prompt ++ out_tokens[:-1] (the generated
    # history re-enters the pool via no-sample chunks — no PRNG key is
    # consumed, so the engine's key stream is untouched) and decode
    # resumes from out_tokens[-1] without re-sampling anything.
    resume: bool = False
    # ctx: the token array the CURRENT allocation's prefill reads
    # (prompt for a fresh admission, prompt ++ out_tokens[:-1] for a
    # resume) — set by _admit, None while queued
    ctx: Optional[np.ndarray] = None
    # epoch: bumped every time the request loses its slot (preemption,
    # restart); in-flight chunks record the epoch they were scheduled
    # against so collection can drop results from a previous life
    epoch: int = 0
    # -- chunked-prefill progress (valid from admission) ------------------
    n_cached: int = 0             # prompt tokens spliced from the cache
    prefill_sent: int = 0         # suffix tokens DISPATCHED so far
    # splice-pending dependencies: (writer request, suffix tokens the
    # writer must have dispatched before our first chunk may read its
    # pages) — see ServingEngine._admit
    deps: List[Tuple["Request", int]] = field(default_factory=list)
    pending_blocks: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    # -- multi-tenant bookkeeping (ISSUE 10) ------------------------------
    # lora_held: this request currently holds one acquire() on its
    # adapter (set at admission, dropped whenever the slot is lost)
    lora_held: bool = False
    # allowed_mask: sampling.allowed_tokens normalized to a [vocab]
    # bool mask at add_request (None = unrestricted)
    allowed_mask: Optional[np.ndarray] = None
    # inter-token latency samples (seconds/token, chunk time split
    # evenly over the chunk's delivered tokens — see _collect_oldest)
    itls: List[float] = field(default_factory=list)
    t_last_emit: Optional[float] = None
    # -- telemetry (ISSUE 12; all None/0 while tracing is off) ------------
    # trace_id: the request's lifetime async-span id on the engine's
    # Tracer — stable across preemption lives AND cross-replica
    # migration (adopt_request continues it), so the whole lifecycle
    # renders as ONE span in Perfetto
    trace_id: Optional[int] = None
    t_queued: float = 0.0         # current queued-life start
    t_life: float = 0.0           # current life's slot-admission time
    t_run: Optional[float] = None   # current life's running transition
    t_wait: Optional[float] = None  # splice-wait start (deps unmet)
    # trace_keep_open: the fleet Router sets this before its drain
    # cancels a request it is about to MIGRATE — the local abort must
    # not close the lifetime span (the adopted continuation on the new
    # replica ends it), or the migrated request would render as two
    # disjoint spans instead of one continuous one
    trace_keep_open: bool = False

    @property
    def prefill_tokens(self) -> np.ndarray:
        """The token array the current prefill reads: the prompt, or
        prompt ++ generated history for a preemption resume."""
        return self.ctx if self.ctx is not None else self.prompt

    @property
    def suffix_len(self) -> int:
        """Prefill tokens that must actually run (past the splice)."""
        return int(len(self.prefill_tokens)) - self.n_cached

    @property
    def deadline_at(self) -> Optional[float]:
        if self.sampling.deadline_s is None:
            return None
        return self.t_submit + self.sampling.deadline_s

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_admit is None:
            return None
        return self.t_admit - self.t_submit

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


def _normalize_prompt(prompt) -> np.ndarray:
    """Prompt intake shared by engine admission and the fleet Router:
    Tensor unwrap, int32 flatten, empty rejection — ONE definition so
    the two surfaces cannot drift."""
    if isinstance(prompt, Tensor):
        prompt = np.asarray(prompt._value)
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    if prompt.size == 0:
        raise ValueError("empty prompt")
    return prompt


def _bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"prompt length {n} exceeds the largest prefill bucket: "
        f"configured prompt_buckets={tuple(buckets)} top out at "
        f"{buckets[-1]} tokens; raise prompt_buckets (or shorten the "
        f"prompt). Oversized prompts are rejected at add_request time "
        f"so they never reach dispatch.")


class ServingEngine:
    """Mixed-length concurrent request serving for a LlamaForCausalLM.

    Usage:
        eng = ServingEngine(model, max_batch_size=8)
        rid = eng.add_request(prompt_ids, SamplingParams(max_new_tokens=64))
        while eng.step():
            pass
        tokens = eng.result(rid)
    """

    def __init__(self, model, max_batch_size: int = 8,
                 num_blocks: int = 512, block_size: int = 16,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256, 512),
                 weight_dtype: Optional[str] = None, top_k: int = 0,
                 chunk_size: int = 8, seed: int = 0,
                 overlap: bool = True, mesh=None,
                 chunk_schedule: Optional[Sequence[int]] = None,
                 prefix_caching: bool = True,
                 prefill_chunk: Optional[int] = 256,
                 prefill_budget: Optional[int] = None,
                 max_dispatch_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 admission: str = "worst_case",
                 max_queue_depth: Optional[int] = None,
                 ragged: bool = False, tp: int = 1,
                 tp_comm: Optional[str] = None,
                 devices: Optional[Sequence] = None,
                 spec_decode: Optional[SpecConfig] = None,
                 lora=None, tracer=None,
                 kv_quant: Optional[str] = None,
                 slo=None,
                 profile_every: Optional[int] = None,
                 profile_seed: int = 0,
                 ragged_idle_cap: Optional[int] = None,
                 multi_step: int = 1):
        from .gpt_decode import PagedGPTDecoder
        # -- multi-chip tensor-parallel serving (ROADMAP 1) -----------------
        # tp=N builds a one-axis "tp" mesh over the first N devices and
        # runs the WHOLE serving step — the ragged [T, W] program,
        # in-program sampling, paged KV append — fully-manual under
        # shard_map: decoder weights placed by the canonical SpecLayout
        # table (wq/wk/wv/wg/wu/head column-parallel, wo/wd
        # row-parallel, embed/norms replicated), the KV pool sharded
        # over the kv-head dim (each shard appends exactly the heads it
        # computed — zero collectives on the append path), exactly ONE
        # allreduce per attention/MLP block plus one all-gather over
        # the per-shard vocab logits before sampling. tp_comm="int8"
        # swaps the block allreduces for the EQuARX-style quantized
        # collective (distributed.collective.int8_all_reduce); the
        # logits gather stays exact. tp>1 forces ragged=True — one
        # sharded program per step IS the multi-chip serving step.
        # tp_comm=None (the default) means "the decoder's mode" —
        # fp32 when the engine builds the decoder itself; an EXPLICIT
        # value that contradicts a prebuilt decoder raises (the comm
        # mode is baked into the decoder's compiled programs, and a
        # silently-substituted mode corrupts exactly the fp32-vs-int8
        # A/B the flag exists for).
        tp = int(tp)
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if tp_comm not in (None, "fp32", "int8"):
            raise ValueError(f"tp_comm must be 'fp32' or 'int8', got "
                             f"{tp_comm!r}")
        # -- multi-step fused decode (ISSUE 16) -----------------------------
        # multi_step=k fuses k consecutive pure-decode serving steps
        # into ONE device program: a lax.scan over k*T ragged decode
        # ministeps with in-program KV append, in-program sampling
        # carried across iterations, and on-device EOS bookkeeping (a
        # per-column live mask freezes finished columns to the scratch
        # slot, so late iterations are no-ops for them). The host
        # collects k*T tokens per column per dispatch, amortizing the
        # host-schedule + dispatch-queue floor the observatory
        # measures. Scheduler invariants (admission, deadlines, epoch
        # guards, preemption, debug_check) move to k-step boundaries:
        # step() dispatches one whole window, so a mid-window cancel
        # or deadline takes effect at the NEXT boundary. Fused windows
        # only dispatch in the pure-decode regime — any prefilling
        # slot drops the engine back to single-step chunks until the
        # prefill drains, so chunked-prefill/splice semantics are
        # untouched. Greedy outputs are token-identical to
        # multi_step=1 (greedy sampling depends only on context, and
        # a window never writes KV a single-step schedule would not).
        multi_step = int(multi_step)
        if multi_step < 1:
            raise ValueError(f"multi_step must be >= 1, got "
                             f"{multi_step}")
        if multi_step > 1 and spec_decode is not None:
            # both features re-schedule the decode token stream on
            # device; composing them (draft windows inside a fused
            # window) is ROADMAP work, not a silent interaction
            raise ValueError(
                "multi_step > 1 and spec_decode are mutually "
                "exclusive: speculative verify windows re-plan every "
                "step from collected acceptance truth, which a fused "
                "k-step program cannot observe mid-window")
        self.multi_step = multi_step
        # -- quantized KV cache (ISSUE 13) ----------------------------------
        # kv_quant="int8" stores the paged pool's k/v planes as int8
        # with per-slot-per-kv-head absmax scales in a sidecar plane:
        # quantize is fused into every append (reshape_and_cache),
        # dequant into every pool read (the ragged Pallas kernel's
        # per-page DMA and the jnp oracle's page walk alike). Roughly
        # halves KV bytes per token (bf16 pools; ~3.6x on f32), so the
        # same HBM holds ~2x the concurrent sequences / resident
        # adapters. None (the default) is the dense pool, bitwise
        # unchanged. ACCURACY CONTRACT: greedy outputs match the fp32
        # pool on the pinned workloads (quantization noise is well
        # below typical logit gaps; a sub-quantization-step near-tie
        # may legitimately flip — that is the flag's contract, same as
        # tp_comm="int8"); note the dense and ragged SCHEDULERS are
        # each deterministic under kv_quant but not bit-identical to
        # each other (dense prefill attends the chunk's fresh
        # full-precision K/V, the ragged path reads its own rows back
        # quantized).
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant must be None or 'int8', got "
                             f"{kv_quant!r}")
        if tp > 1 and mesh is not None:
            raise ValueError("pass either tp=N (manual shard_map "
                             "serving) or mesh= (GSPMD decoder "
                             "placement), not both")
        if isinstance(model, (PagedLlamaDecoder, PagedGPTDecoder)):
            # a prebuilt paged decoder (e.g. PagedLlamaDecoder
            # .from_config for 8B-class weights that must be quantized
            # at load); its pool/quantization/tp choices stand — the
            # num_blocks/block_size/weight_dtype args here are ignored
            if devices is not None:
                raise ValueError(
                    "devices= only applies when the engine builds the "
                    "decoder itself; a prebuilt decoder's mesh already "
                    "fixed its device placement")
            self.dec = model
            dec_tp = int(getattr(model, "_tp", 1))
            if tp > 1 and dec_tp != tp:
                raise ValueError(
                    f"ServingEngine(tp={tp}) got a prebuilt decoder "
                    f"with tp degree {dec_tp}; build the decoder with "
                    f"the matching mesh (tp_shard_map=True) or drop "
                    f"the engine tp argument")
            dec_comm = getattr(model, "tp_comm", "fp32")
            if tp_comm is not None and dec_comm != tp_comm:
                # the comm mode is baked into the decoder's programs:
                # silently substituting the decoder's would run the
                # wrong leg of the fp32-vs-int8 A/B in EITHER direction
                raise ValueError(
                    f"ServingEngine(tp_comm={tp_comm!r}) got a "
                    f"prebuilt decoder built with tp_comm="
                    f"{dec_comm!r}; pass the desired tp_comm to the "
                    f"decoder constructor instead")
            dec_kvq = getattr(model.cache, "kv_quant", None)
            if kv_quant is not None and dec_kvq != kv_quant:
                # same contract as tp_comm: the pool layout is baked
                # into the decoder's cache and compiled programs — a
                # silently-substituted mode would run the wrong leg of
                # the fp32-vs-int8 capacity/accuracy A/B
                raise ValueError(
                    f"ServingEngine(kv_quant={kv_quant!r}) got a "
                    f"prebuilt decoder whose pool was built with "
                    f"kv_quant={dec_kvq!r}; pass the desired kv_quant "
                    f"to the decoder constructor instead")
            self.tp = dec_tp
        else:
            if devices is not None and tp == 1:
                # fail loudly, like the PR-8 tp-flag checks: a tp=1
                # engine always builds on the default device, and a
                # silently-dropped placement request would put every
                # "placed" fleet replica on one chip with no hint why
                raise ValueError(
                    "devices= requires tp > 1: a single-chip engine "
                    "builds on the default device (the fleet Router "
                    "passes devices only for tp-sharded replicas)")
            if tp > 1:
                # devices=: an explicit device slice for the tp mesh —
                # the fleet Router (inference/fleet.py) places each
                # dp replica's tp mesh on a DISJOINT row of the
                # SpecLayout dp x tp device grid; the default remains
                # the first tp devices of the process
                devs = (list(devices) if devices is not None
                        else jax.devices())
                if len(devs) < tp:
                    raise ValueError(
                        f"tp={tp} needs {tp} devices, found "
                        f"{len(devs)}")
                from jax.sharding import Mesh
                mesh = Mesh(np.asarray(devs[:tp]), ("tp",))
            self.dec = PagedLlamaDecoder(model, num_blocks=num_blocks,
                                         block_size=block_size,
                                         weight_dtype=weight_dtype,
                                         mesh=mesh, mp_axis="tp"
                                         if tp > 1 else "mp",
                                         tp_shard_map=tp > 1,
                                         tp_comm=tp_comm or "fp32",
                                         kv_quant=kv_quant)
            self.tp = tp
        self.tp_comm = getattr(self.dec, "tp_comm", tp_comm or "fp32")
        # the pool's actual quantization mode (prebuilt decoders carry
        # their own; None = dense fp planes) — surfaced by stats()
        self.kv_quant = getattr(self.dec.cache, "kv_quant", None)
        self.max_b = int(max_batch_size)
        self.buckets = tuple(sorted(prompt_buckets))
        self.top_k = int(top_k)
        # chunk ladder (adaptive decode granularity): each dispatch
        # picks a rung via _pick_chunk — after warmup, the rung
        # maximizing measured tokens/sec for the current slot budgets
        # (big chunks amortize host round trips; small chunks keep slot
        # turnover and admission prompt). Single-entry schedule (the
        # default) = fixed chunk.
        if chunk_schedule:
            self.chunks = tuple(sorted({max(1, int(c))
                                        for c in chunk_schedule}))
        else:
            self.chunks = (max(1, int(chunk_size)),)
        self.chunk = self.chunks[0]
        # overlap: dispatch decode chunk t+1 (first tokens taken from
        # chunk t's DEVICE output) before fetching chunk t's tokens, so
        # host admission/bookkeeping runs while the device decodes.
        # Falls back to synchronous collection while any active request
        # uses repetition_penalty (its seen-mask needs fetched history).
        self.overlap = bool(overlap)
        self._key = jax.random.PRNGKey(seed)
        cache = self.dec.cache
        # reserve one scratch page: pad-token prefill writes and inactive
        # decode slots land here, never in a live page (a prebuilt
        # decoder reused across engines keeps its existing scratch page)
        if -1 not in cache._tables:
            cache.allocate(-1, 1)
        self._scratch_block = cache._tables[-1][0]
        self._scratch_slot = self._scratch_block * cache.block_size
        # automatic prefix caching: block-granular KV reuse on admission
        # (needs the decoder's suffix-prefill program — prebuilt
        # decoders without one fall back to full prefills)
        self.prefix_caching = bool(prefix_caching) and \
            hasattr(self.dec, "_prefill_prefix_impl")
        # chunked prefill (the stall-free interleaving path): suffixes
        # longer than prefill_chunk split into fixed-size chunks that
        # interleave with decode chunks. Needs the decoder's chunk
        # program; prefill_chunk=None restores monolithic prefill
        # (whole suffix in one dispatch — still queued/async, so the
        # ONLY behavioral difference is the device-side interleaving).
        self.prefill_chunk = (int(prefill_chunk)
                              if prefill_chunk and
                              hasattr(self.dec, "_prefill_chunk_impl")
                              else None)
        # per-step prefill token budget while decodes are running
        # (idle engines dispatch every ready chunk): at most ~budget
        # prefill tokens slot between consecutive decode chunks, which
        # is the running streams' worst-case added inter-token latency
        self.prefill_budget = max(1, int(prefill_budget)) \
            if prefill_budget else (self.prefill_chunk or 0)
        # -- fault tolerance ------------------------------------------------
        # bounded retry with exponential backoff around every device
        # dispatch/fetch: a transient error re-tries the SAME call
        # (same args, same PRNG key — token-identical on success);
        # exhaustion fails the involved requests, never the engine.
        self.max_dispatch_retries = max(0, int(max_dispatch_retries))
        self.retry_backoff_s = float(retry_backoff_s)
        # admission policy: "worst_case" reserves prompt+max_new pages
        # up front (a running request can never hit pool exhaustion —
        # the PR-1 invariant); "optimistic" reserves only the prefill's
        # pages and grows on demand, oversubscribing the pool — under
        # pressure the engine preempts the newest/lowest-priority
        # running request (frees its blocks, re-enqueues it as a
        # no-sample chunked re-prefill that rides the prefix cache).
        if admission not in ("worst_case", "optimistic"):
            raise ValueError(
                f"admission must be 'worst_case' or 'optimistic', "
                f"got {admission!r}")
        self.admission = admission
        self.max_queue_depth = (int(max_queue_depth)
                                if max_queue_depth is not None else None)
        # robustness counters (stats(); reset by clear_finished)
        self.preemptions = 0
        self.recompute_tokens = 0
        self.aborted = 0
        self.failed = 0
        self.deadline_misses = 0
        self.shed_requests = 0
        self.retries = 0
        # dispatch/fetch calls that exhausted their whole retry budget
        # (each one failed the involved requests). This is the fleet
        # Router's primary per-replica health signal: a replica whose
        # engine keeps exhausting _device_call retries is wedged, not
        # merely flaky (reset by clear_finished)
        self.dispatch_exhaustions = 0
        # device-program launch count (every successful "dispatch:*"
        # _device_call — prefill, decode, merge, ragged, spec); with
        # generated_tokens it yields tokens_per_dispatch, the headline
        # the ragged path optimizes and speculative decoding multiplies
        # (accepted draft tokens are generated_tokens too, so the
        # metric reflects the win; reset by clear_finished)
        self.device_dispatches = 0
        # speculative-decoding counters (ISSUE 9; reset by
        # clear_finished): drafted = draft rows dispatched for
        # verification, accepted = drafts confirmed by the teacher,
        # spec_rollbacks = verify steps that rejected >= 1 draft (each
        # costs one PagedKVCache.rollback of the rejected tail)
        self.drafted_tokens = 0
        self.accepted_draft_tokens = 0
        self.spec_rollbacks = 0
        # optional chaos monkey (utils/chaos.py ChaosMonkey.attach):
        # consulted by _device_call before every dispatch/fetch
        self.chaos = None
        # static prefix-gather width: a hit prefix is < the prompt, and
        # prompts are bounded by the largest bucket
        self._prefix_pages = -(-self.buckets[-1] // cache.block_size)
        # mid-chunk prefix widths are power-of-two BUCKETED: chunk i's
        # prefix is only i*C tokens, and paying the max-bucket gather +
        # masked attention on every chunk made early chunks cost as
        # much as late ones (the chunk program is width-1 and runs
        # O(prompt/C) times per long prompt, so ~log2 variants are
        # cheap; the one-shot final keeps the single max-width program
        # shared with the prefix-cache-hit path)
        self._prefix_page_buckets = []
        p = 1
        while p < self._prefix_pages:
            self._prefix_page_buckets.append(p)
            p *= 2
        self._prefix_page_buckets.append(self._prefix_pages)
        # recompute prefills (preemption resume) run at offsets up to
        # prompt + generated history — past the largest prompt bucket —
        # so the mid-chunk prefix ladder continues doubling up to the
        # longest table a single sequence can hold. Entries after
        # _prefix_pages are only ever reached by resumes, so the
        # pre-existing bucket choices (and compiled variants) of the
        # normal chunked-prefill path are unchanged.
        cap_pages = min(self.dec.max_pages,
                        max(1, cache.num_blocks - 1))
        while p < cap_pages and self._prefix_page_buckets[-1] < cap_pages:
            if p > self._prefix_page_buckets[-1]:
                self._prefix_page_buckets.append(min(p, cap_pages))
            p *= 2
        if self._prefix_page_buckets[-1] < cap_pages:
            self._prefix_page_buckets.append(cap_pages)
        # chunk width for preemption-resume prefills: ride the chunked-
        # prefill programs when enabled, else a dedicated 64-wide rung
        self._recompute_chunk = self.prefill_chunk or 64
        self._debug_pool = os.environ.get(
            "PADDLE_TPU_POOL_DEBUG", "") not in ("", "0")
        # schedule-array staging: under manual tp the per-chunk arrays
        # must reach the program UNCOMMITTED (np) — jnp.asarray would
        # commit them to the default device, which conflicts with the
        # tp mesh; jit places uncommitted arrays per the shard_map
        # in_specs (replicated) itself
        self._aj = jnp.asarray if self.tp == 1 else np.asarray

        self._slots: List[Optional[Request]] = [None] * self.max_b
        self._last_tok = np.zeros(self.max_b, np.int32)
        self._queue: deque = deque()
        self._done: Dict[int, Request] = {}
        self._ids = itertools.count()
        self.decode_steps = 0
        self.generated_tokens = 0
        # decode-utilization accounting (chunk-ladder tuning): a decode
        # dispatch runs T steps x max_b slots regardless of how many
        # slots had real work — slot_steps counts everything the
        # program ran, useful_tokens what reached a request
        self.decode_slot_steps = 0
        self.decode_useful_tokens = 0
        # splice-pending writer index: block -> (writer request, suffix
        # tokens the writer must dispatch for the block to be written);
        # entries live only while the writer is mid-prefill
        self._pending_writes: Dict[int, Tuple[Request, int]] = {}
        # async pipeline state (overlap mode): dispatched, unfetched
        # prefill AND decode chunks, in device program order
        self._inflight: deque = deque()
        self._fresh_slots: set = set()    # slots (re)filled since the
        #                                   last dispatch: their first
        #                                   token comes from the host
        # phase-time breakdown (bench: prefill / decode-stall / host)
        self.time_prefill_s = 0.0
        self.time_stall_s = 0.0
        self.time_host_s = 0.0
        self._zeros_seen_cache: Dict[int, jax.Array] = {}
        # per-rung measured chunk cost (seconds/chunk), built by warmup;
        # empty → _pick_chunk uses the zero-waste heuristic
        self._chunk_cost: Dict[int, float] = {}
        self._force_chunk: Optional[int] = None

        dec = self.dec

        def prefill(weights, k, v, ids, slots, last_idx, temp, key,
                    top_ks, top_ps, rep, seen, allowed):
            logits, k, v = dec._prefill_impl(weights, k, v, ids, slots,
                                             last_idx)
            tok = self._sample_rich(logits, temp, key, top_ks, top_ps,
                                    rep, seen, allowed)
            return tok, k, v

        def prefill_prefix(weights, k, v, ids, slots, last_idx,
                           n_cached, prefix_tables, temp, key, top_ks,
                           top_ps, rep, seen, allowed):
            logits, k, v = dec._prefill_prefix_impl(
                weights, k, v, ids, slots, last_idx, n_cached,
                prefix_tables)
            tok = self._sample_rich(logits, temp, key, top_ks, top_ps,
                                    rep, seen, allowed)
            return tok, k, v

        def decode_chunk(weights, k, v, first_ids, tables_all, ctx_all,
                         slots_all, temp, keys_all):
            """T decode steps as one lax.scan (one dispatch per chunk)."""
            def step(carry, xs):
                last_ids, kp, vp = carry
                tables, ctx, slots, key = xs
                logits, kp, vp = dec._decode_logits(
                    weights, kp, vp, last_ids, tables, ctx, slots)
                nxt = self._sample(logits, temp, key)
                return (nxt, kp, vp), nxt
            (_, k, v), toks = jax.lax.scan(
                step, (first_ids, k, v),
                (tables_all, ctx_all, slots_all, keys_all))
            return toks.swapaxes(0, 1), k, v   # [b, T]

        def decode_chunk_rich(weights, k, v, first_ids, tables_all,
                              ctx_all, slots_all, temp, keys_all,
                              top_ks, top_ps, rep, seen, allowed):
            """Per-request-sampling variant: the scan additionally
            carries the token-presence mask (repetition penalty) and
            applies per-slot top_k/top_p masks plus the per-slot
            allowed-vocab mask (structured decoding). Compiled only
            when a request actually asks for them."""
            def step(carry, xs):
                last_ids, kp, vp, seen_c = carry
                tables, ctx, slots, key = xs
                logits, kp, vp = dec._decode_logits(
                    weights, kp, vp, last_ids, tables, ctx, slots)
                nxt = self._sample_rich(logits, temp, key, top_ks,
                                        top_ps, rep, seen_c, allowed)
                seen_c = seen_c.at[
                    jnp.arange(seen_c.shape[0]), nxt].set(True)
                return (nxt, kp, vp, seen_c), nxt
            (_, k, v, _), toks = jax.lax.scan(
                step, (first_ids, k, v, seen),
                (tables_all, ctx_all, slots_all, keys_all))
            return toks.swapaxes(0, 1), k, v   # [b, T]

        def merge_first(toks_dev, last_idx, overrides, use_host):
            """First tokens of the next chunk from the previous chunk's
            device output (continuing slots) or host values (fresh
            slots) — keeps the chunk-to-chunk dependency on-device."""
            gathered = toks_dev[jnp.arange(toks_dev.shape[0]), last_idx]
            return jnp.where(use_host, overrides, gathered)

        self._prefill_j = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefill_prefix_j = jax.jit(prefill_prefix,
                                         donate_argnums=(1, 2))
        self._decode_j = jax.jit(decode_chunk, donate_argnums=(1, 2))
        self._decode_rich_j = jax.jit(decode_chunk_rich,
                                      donate_argnums=(1, 2))
        self._merge_first_j = jax.jit(merge_first)
        if hasattr(dec, "_prefill_chunk_impl"):
            # no-sample chunk programs (width 1, exactly prefill_chunk
            # tokens; prefill_mid retraces per power-of-two prefix-
            # width bucket — ~log2(prefix_pages) variants — plus one
            # cold-start prefill_mid0): mid chunks only write K/V, so
            # the wrappers drop the logits and XLA DCEs the head
            # matmul; no PRNG key is consumed. Built even with chunked
            # prefill OFF: preemption-with-recompute re-prefills a
            # preempted request's history through these (the resume
            # must not draw PRNG keys, or every other request's
            # sampled stream would shift vs a fault-free run).
            def prefill_mid(weights, k, v, ids, slots, n_cached, ptab):
                return dec._prefill_chunk_impl(weights, k, v, ids,
                                               slots, n_cached, ptab)

            def prefill_mid0(weights, k, v, ids, slots):
                _, k, v = dec._prefill_impl(weights, k, v, ids, slots)
                return k, v

            self._prefill_mid_j = jax.jit(prefill_mid,
                                          donate_argnums=(1, 2))
            self._prefill_mid0_j = jax.jit(prefill_mid0,
                                           donate_argnums=(1, 2))
        self._can_recompute = hasattr(dec, "_prefill_chunk_impl")

        # -- ragged unified prefill+decode batching (ISSUE 5) ---------------
        # ragged=True collapses every per-step dispatch into ONE device
        # program: a [T, W] schedule of flattened ragged rows — decode
        # rows (one column per running slot, T sequential ministeps,
        # sampled in-program with the previous chunk's device output
        # merged IN-program, so there is no separate merge dispatch) and
        # prefill rows (no-sample mid-chunk rows at their global offsets;
        # a prompt's final token row samples the request's first token).
        # W is sized by the ACTUAL rows (bucketed), not max_batch — the
        # dense path's scratch-slot padding disappears at the source.
        # Needs the decoder's _ragged_logits; the attention op falls
        # back to the masked jnp oracle off-TPU.
        self.ragged = bool(ragged) and hasattr(dec, "_ragged_logits")
        if self.tp > 1:
            if not hasattr(dec, "_ragged_logits"):
                raise ValueError(
                    "tensor-parallel serving needs a decoder with the "
                    "ragged step program (_ragged_logits)")
            # the tp serving step IS the sharded ragged program; the
            # dense per-phase dispatch path is not built for shard_map
            self.ragged = True
        if self.multi_step > 1:
            if not hasattr(dec, "_ragged_logits"):
                raise ValueError(
                    "multi-step fused decode needs a decoder with the "
                    "ragged step program (_ragged_logits)")
            # the fused window IS a ragged [k*T, W] program
            self.ragged = True
        # -- speculative decoding (ISSUE 9) ---------------------------------
        # spec_decode=SpecConfig(...): each greedy decode column's k
        # draft tokens ride as EXTRA ROWS of the ragged program (the
        # mechanism prefill-chunk rows already use) and are verified
        # in-program — teacher logits at every draft position in ONE
        # forward, longest-accepted-prefix acceptance, rejected tails'
        # pool writes neutralized via the scratch page and their slots
        # rescinded by PagedKVCache.rollback. Up to draft_len + 1
        # verified tokens per column per dispatch; greedy outputs are
        # BIT-IDENTICAL to the spec-off path (each emitted token is
        # the teacher's own argmax under a verified prefix). Forces
        # the ragged path: the verify window IS a ragged row pattern.
        # -- multi-tenant many-LoRA serving (ISSUE 10) ----------------------
        # lora=AdapterRegistry(...): per-request adapters ride the
        # ragged [T, W] program as per-row (A, B) deltas gathered from
        # adapter pages paged through the SAME block pool as the KV
        # cache (S-LoRA style — see inference/lora.py). Forces the
        # ragged path: the per-row adapter index IS a ragged-row
        # attribute. Dispatches whose scheduled requests are all
        # base-model use the UNCHANGED base programs, so adapter_id=
        # None traffic is bit-identical to a lora-less engine.
        self.lora = lora
        if lora is not None:
            from .lora import AdapterRegistry
            if not isinstance(lora, AdapterRegistry):
                raise TypeError(
                    f"lora must be an AdapterRegistry, got "
                    f"{type(lora).__name__}")
            if not hasattr(dec, "_ragged_logits") \
                    or not hasattr(dec, "lora_target_modules"):
                raise ValueError(
                    "many-LoRA serving needs a decoder with the ragged "
                    "step program and LoRA targets (_ragged_logits + "
                    "lora_target_modules)")
            self.ragged = True
            if self.tp > 1:
                # the plane's placement comes from the canonical
                # SpecLayout table (replicated), like every other
                # sharded serving array
                lora.bind(dec, sharding=dec._layout().sharding(
                    dec.mesh, "lora_pool"))
            else:
                lora.bind(dec)
        # per-shard index operand for the lora programs: a tp-sharded
        # arange whose in-program element is the shard id (the repo's
        # axis_index idiom — jax 0.4.x-safe); a plain [0] off tp
        if self.tp > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            self._shard_ids = jax.device_put(
                np.arange(self.tp, dtype=np.int32),
                NamedSharding(self.dec.mesh, P("tp")))
        else:
            self._shard_ids = np.zeros(1, np.int32)
        # multi-tenant / structured-decoding counters (stats(); reset
        # by clear_finished): lora_dispatches / lora_rows feed
        # lora_rows_per_dispatch; masked_decode_columns counts
        # scheduled decode columns carrying an allowed_tokens mask
        self.lora_dispatches = 0
        self.lora_rows = 0
        self.masked_decode_columns = 0
        # multi-step fused decode counters (stats(); reset by
        # clear_finished): windows dispatched, and slot-steps a fused
        # window scheduled but froze after an in-window EOS (the
        # honest frozen-column share of padded_token_waste)
        self.ms_windows = 0
        self.ms_frozen_token_waste = 0
        self._ones_allowed_cache: Dict[int, jax.Array] = {}
        # composed allowed-mask operands, memoized per (rows, row ->
        # mask-identity) layout: a request's mask is immutable, so a
        # steady-state masked stream re-ships nothing (cleared by
        # clear_finished — mask ids are only stable while their
        # requests are retained)
        self._allowed_memo: Dict[tuple, jax.Array] = {}
        # -- telemetry (ISSUE 12) -------------------------------------------
        # tracer=None (the default) is a bitwise no-op: every hook is
        # behind an `if self.tracer is not None` guard, no PRNG key is
        # drawn and no schedule array changes. set_telemetry also
        # threads the tracer into the KV pool and the adapter registry
        # so kv alloc/evict/splice/rollback and adapter refaults land
        # in the same flight recorder; the fleet Router re-calls it
        # with the replica index so every record carries its replica.
        # -- program observatory (ISSUE 14) ---------------------------------
        # CompileWatch: every serving program family registers its
        # jitted callable (end of __init__, once the programs exist);
        # _device_call asks the watch after each dispatch whether the
        # jit cache grew — a grown cache IS a trace+lower+compile,
        # recorded as a compile span. seal_programs() (after
        # warmup_programs has compiled the reachable grid) turns any
        # later compile into engine.unexpected_recompiles — the
        # runtime analogue of flightcheck's FC2xx rules. The watch is
        # always on: detection is two host attribute reads per
        # dispatch, and chaos legs must be able to assert the sealed
        # contract even when no tracer is attached.
        self.compile_watch = CompileWatch()
        self.unexpected_recompiles = 0
        self.program_compiles = 0
        # sampled dispatch-time attribution: every profile_every-th
        # dispatch pays a block_until_ready fence (seeded start phase)
        # and splits the step wall into host-schedule / dispatch-queue
        # / device-execute histograms, per program family. Default OFF
        # — the unsampled steady state keeps the async pipeline and
        # the bitwise no-op contract (a fence never changes tokens,
        # but it does cost a sync, so sampling is opt-in).
        if profile_every is not None and int(profile_every) < 1:
            raise ValueError(f"profile_every must be >= 1, got "
                             f"{profile_every}")
        self._prof_n = int(profile_every) if profile_every else 0
        self._prof_metrics = None    # lazy registry when tracer is off
        self._prof_countdown = 0
        if self._prof_n:
            rng = np.random.RandomState(int(profile_seed))
            self._prof_countdown = 1 + int(rng.randint(self._prof_n))
        self._prof_mark = time.perf_counter()
        self.profiled_dispatches = 0
        # SLO monitoring (declared per-class latency targets; see
        # telemetry.SLOPolicy/SLOMonitor): fed at the same collection
        # points as the PR-12 histograms, evaluated by stats()["slo"].
        # Pure host-side and passive — attaching a monitor changes no
        # schedule, draws no key.
        if isinstance(slo, SLOMonitor):
            self._slo = slo
        else:
            pols = SLOMonitor.coerce_policies(slo)
            self._slo = SLOMonitor(pols) if pols else None
        self._slo_violating: set = set()
        # per-window draft-acceptance EMA (alpha 0.1): the adaptive-
        # window signal ROADMAP item 2 needs, sampled into the
        # acceptance_ema counter track
        self.draft_acceptance_ema = 0.0
        self.set_telemetry(tracer)
        # bounded ITL aggregation (ISSUE 12 satellite): finished
        # requests' per-token samples fold into a seeded reservoir at
        # retire time, so stats() percentiles stay O(k) on unbounded
        # runs (exact below capacity; sampling-tolerance above it).
        # Live requests' samples are still read exactly from the slot.
        self._itl_res = Reservoir(self.ITL_RESERVOIR_K)
        self.spec = spec_decode
        self._drafter = None
        if self.spec is not None:
            if not isinstance(self.spec, SpecConfig):
                raise TypeError(
                    f"spec_decode must be a SpecConfig, got "
                    f"{type(self.spec).__name__}")
            if not (hasattr(dec, "_ragged_logits")
                    and hasattr(dec, "_spec_accept")):
                raise ValueError(
                    "speculative decoding needs a decoder with the "
                    "ragged step program and the verification tail "
                    "(_ragged_logits + _spec_accept)")
            self.ragged = True
            self._drafter = self.spec.make_drafter()
        # prefill tokens folded into one ragged dispatch (the ragged
        # path is always chunked-style — a long prompt spreads over
        # successive steps' programs under this per-step cap)
        self._ragged_cap = (self.prefill_budget or self.prefill_chunk
                            or self._recompute_chunk)
        # idle-drain width bound (ISSUE 14): pure-prefill programs on
        # an idle engine widen up to this many rows per dispatch. The
        # class default keeps the PR-5 wide-drain behavior; a bounded
        # value CLOSES the reachable (T, W) program grid so
        # warmup_programs can compile it whole and seal_programs can
        # assert no mid-run retrace (the chaos legs run bounded)
        if ragged_idle_cap is not None and int(ragged_idle_cap) < 1:
            raise ValueError(f"ragged_idle_cap must be >= 1, got "
                             f"{ragged_idle_cap}")
        self._ragged_idle_cap = (int(ragged_idle_cap)
                                 if ragged_idle_cap is not None
                                 else self._RAGGED_IDLE_CAP)
        self._zeros_toks_cache: Dict[Tuple[int, int], jax.Array] = {}
        if self.ragged:
            def ragged_chunk(weights, k, v, prev_toks, last_t, prev_col,
                             use_host, override, ids_all, pos_all,
                             slots_all, rseq_all, rctx_all, use_carry,
                             tables, temps_all, keys):
                """T ragged ministeps as one lax.scan. Decode columns
                carry their sampled token ministep-to-ministep on
                device; their FIRST token is gathered from the previous
                ragged chunk's [T, W] output (continuing columns) or a
                host override (fresh slots) — the dense path's
                merge_first folded into the program."""
                first = jnp.where(use_host, override,
                                  prev_toks[last_t, prev_col])

                def step(carry, xs):
                    cur, kp, vp = carry
                    ids_d, pos, slots, rseq, rctx, uc, temp, key = xs
                    ids = jnp.where(uc, cur, ids_d)
                    logits, kp, vp = dec._ragged_logits(
                        weights, kp, vp, ids, pos, slots, rseq, rctx,
                        tables)
                    nxt = self._sample(logits, temp, key)
                    return (nxt, kp, vp), nxt

                (_, k, v), toks = jax.lax.scan(
                    step, (first, k, v),
                    (ids_all, pos_all, slots_all, rseq_all, rctx_all,
                     use_carry, temps_all, keys))
                return toks, k, v          # [T, W]

            def ragged_chunk_rich(weights, k, v, prev_toks, last_t,
                                  prev_col, use_host, override, ids_all,
                                  pos_all, slots_all, rseq_all,
                                  rctx_all, use_carry, tables,
                                  temps_all, keys, top_ks_all,
                                  top_ps_all, reps_all, seen, upd,
                                  allowed):
                """Per-request-sampling twin: carries the seen mask.
                Only columns flagged in `upd` (decode columns)
                accumulate their own samples — a final-prefill row's
                seen mask is its prompt, seeded host-side, and other
                ministeps sharing its column must not pollute it.
                ``allowed`` [W, vocab] is per COLUMN (ministep-
                invariant): only the column's consumed cells — its
                decode samples or its one sampling final — ever reach
                a request, so masking the discarded cells too is
                harmless."""
                first = jnp.where(use_host, override,
                                  prev_toks[last_t, prev_col])
                w = use_host.shape[0]

                def step(carry, xs):
                    cur, kp, vp, seen_c = carry
                    (ids_d, pos, slots, rseq, rctx, uc, temp, key,
                     tks, tps, rp) = xs
                    ids = jnp.where(uc, cur, ids_d)
                    logits, kp, vp = dec._ragged_logits(
                        weights, kp, vp, ids, pos, slots, rseq, rctx,
                        tables)
                    nxt = self._sample_rich(logits, temp, key, tks,
                                            tps, rp, seen_c, allowed)
                    rows = jnp.arange(w)
                    seen_c = seen_c.at[rows, nxt].set(
                        seen_c[rows, nxt] | upd)
                    return (nxt, kp, vp, seen_c), nxt

                (_, k, v, _), toks = jax.lax.scan(
                    step, (first, k, v, seen),
                    (ids_all, pos_all, slots_all, rseq_all, rctx_all,
                     use_carry, temps_all, keys, top_ks_all,
                     top_ps_all, reps_all))
                return toks, k, v          # [T, W]

            if self.tp > 1:
                # the WHOLE step program — decode scan, in-program
                # sampling, KV append, prefill rows — runs fully-manual
                # under shard_map on the tp mesh (jax 0.4.x cannot
                # lower collectives in a partially-manual region; the
                # one-axis serving mesh makes full specs natural)
                self._ragged_j = jax.jit(
                    dec.tp_wrap(ragged_chunk, n_extra=14),
                    donate_argnums=(1, 2))
                self._ragged_rich_j = jax.jit(
                    dec.tp_wrap(ragged_chunk_rich, n_extra=20),
                    donate_argnums=(1, 2))
            else:
                self._ragged_j = jax.jit(ragged_chunk,
                                         donate_argnums=(1, 2))
                self._ragged_rich_j = jax.jit(ragged_chunk_rich,
                                              donate_argnums=(1, 2))

            if self.lora is not None:
                layout = self.lora.layout

                def _lora_ctx(lora_pool, shard_ids, lora_tables):
                    """Gather each engine slot's adapter pages out of
                    the shared pool plane ONCE per dispatch (scan-
                    invariant): [S, n_pages * page_elems] flat factors
                    the decoder's static layout slices — S = max_b + 1
                    rows addressed by row_seq, the scratch row reading
                    the scratch block's all-zero page (the null
                    adapter every base-only row costs)."""
                    # bounded, deliberate: S * n_pages adapter pages
                    # (the slots' own tables, not the pool), gathered
                    # once per dispatch outside the decode scan
                    flat = jnp.take(  # flightcheck: disable=FC701
                        lora_pool, lora_tables.reshape(-1),
                        axis=0, mode="clip")
                    flat = flat.reshape(lora_tables.shape[0], -1)
                    return (layout, flat, shard_ids[0])

                def ragged_lora_chunk(weights, k, v, lora_pool,
                                      shard_ids, lora_tables,
                                      prev_toks, last_t, prev_col,
                                      use_host, override, ids_all,
                                      pos_all, slots_all, rseq_all,
                                      rctx_all, use_carry, tables,
                                      temps_all, keys):
                    """ragged_chunk with per-row LoRA deltas: the
                    multi-tenant twin — same schedule contract, one
                    program per step, adapters applied inside
                    _ragged_logits via the gathered page factors."""
                    lctx = _lora_ctx(lora_pool, shard_ids, lora_tables)
                    first = jnp.where(use_host, override,
                                      prev_toks[last_t, prev_col])

                    def step(carry, xs):
                        cur, kp, vp = carry
                        ids_d, pos, slots, rseq, rctx, uc, temp, key \
                            = xs
                        ids = jnp.where(uc, cur, ids_d)
                        logits, kp, vp = dec._ragged_logits(
                            weights, kp, vp, ids, pos, slots, rseq,
                            rctx, tables, lora=lctx)
                        nxt = self._sample(logits, temp, key)
                        return (nxt, kp, vp), nxt

                    (_, k, v), toks = jax.lax.scan(
                        step, (first, k, v),
                        (ids_all, pos_all, slots_all, rseq_all,
                         rctx_all, use_carry, temps_all, keys))
                    return toks, k, v          # [T, W]

                def ragged_lora_chunk_rich(weights, k, v, lora_pool,
                                           shard_ids, lora_tables,
                                           prev_toks, last_t, prev_col,
                                           use_host, override, ids_all,
                                           pos_all, slots_all,
                                           rseq_all, rctx_all,
                                           use_carry, tables,
                                           temps_all, keys, top_ks_all,
                                           top_ps_all, reps_all, seen,
                                           upd, allowed):
                    """ragged_chunk_rich with per-row LoRA deltas."""
                    lctx = _lora_ctx(lora_pool, shard_ids, lora_tables)
                    first = jnp.where(use_host, override,
                                      prev_toks[last_t, prev_col])
                    w = use_host.shape[0]

                    def step(carry, xs):
                        cur, kp, vp, seen_c = carry
                        (ids_d, pos, slots, rseq, rctx, uc, temp, key,
                         tks, tps, rp) = xs
                        ids = jnp.where(uc, cur, ids_d)
                        logits, kp, vp = dec._ragged_logits(
                            weights, kp, vp, ids, pos, slots, rseq,
                            rctx, tables, lora=lctx)
                        nxt = self._sample_rich(logits, temp, key, tks,
                                                tps, rp, seen_c,
                                                allowed)
                        rows = jnp.arange(w)
                        seen_c = seen_c.at[rows, nxt].set(
                            seen_c[rows, nxt] | upd)
                        return (nxt, kp, vp, seen_c), nxt

                    (_, k, v, _), toks = jax.lax.scan(
                        step, (first, k, v, seen),
                        (ids_all, pos_all, slots_all, rseq_all,
                         rctx_all, use_carry, temps_all, keys,
                         top_ks_all, top_ps_all, reps_all))
                    return toks, k, v          # [T, W]

                if self.tp > 1:
                    self._ragged_lora_j = jax.jit(
                        dec.tp_wrap(ragged_lora_chunk, n_extra=15,
                                    lora_pool=True),
                        donate_argnums=(1, 2))
                    self._ragged_lora_rich_j = jax.jit(
                        dec.tp_wrap(ragged_lora_chunk_rich, n_extra=21,
                                    lora_pool=True),
                        donate_argnums=(1, 2))
                else:
                    self._ragged_lora_j = jax.jit(
                        ragged_lora_chunk, donate_argnums=(1, 2))
                    self._ragged_lora_rich_j = jax.jit(
                        ragged_lora_chunk_rich, donate_argnums=(1, 2))

            if self.spec is not None:
                scratch = self._scratch_slot

                def spec_chunk(weights, k, v, override, use_ov, ids,
                               pos, slots, rseq, rctx, tables, temps,
                               key, seg_start, is_draft):
                    """ONE speculative verify+decode ministep over a
                    ragged [W] row batch: each decode column's carried
                    token plus its k draft rows at consecutive
                    positions (drafts condition on each other through
                    the pool — write-before-attend + row_ctx, the
                    prefill-chunk mechanism), prefill rows riding
                    along as usual. Per-row sampling gives the
                    teacher's token at every position in one forward;
                    the decoder's _spec_accept computes the
                    longest-accepted-prefix mask in-program and
                    neutralizes rejected rows' pool writes via the
                    scratch slot. No scan: acceptance decides the next
                    input token, so a verify chunk is one ministep and
                    the host schedules the next from collected truth.
                    """
                    ids_in = jnp.where(use_ov, override, ids)
                    logits, k, v = dec._ragged_logits(
                        weights, k, v, ids_in, pos, slots, rseq, rctx,
                        tables)
                    toks = self._sample(logits, temps, key)
                    acc, k, v = dec._spec_accept(
                        k, v, toks, ids, slots, seg_start, is_draft,
                        scratch)
                    return toks, acc, k, v

                if self.tp > 1:
                    # verification must stay one-allreduce-per-block:
                    # _spec_accept compares post-gather (replicated)
                    # tokens and zero-scatters per-shard kv-head
                    # slices, so the sharded verify program has
                    # EXACTLY the T=1 ragged program's collectives
                    # (pinned by comm_audit serving.ragged_spec_tp2)
                    self._spec_j = jax.jit(
                        dec.tp_wrap(spec_chunk, n_extra=12,
                                    outs="takv"),
                        donate_argnums=(1, 2))
                else:
                    self._spec_j = jax.jit(spec_chunk,
                                           donate_argnums=(1, 2))

                if self.lora is not None:
                    def spec_lora_chunk(weights, k, v, lora_pool,
                                        shard_ids, lora_tables,
                                        override, use_ov, ids, pos,
                                        slots, rseq, rctx, tables,
                                        temps, key, seg_start,
                                        is_draft):
                        """spec_chunk with per-row LoRA deltas: draft
                        rows verify against the ROW's adapter model
                        (base + its tenant's delta), so acceptance is
                        exact per tenant; the acceptance tail is
                        adapter-agnostic."""
                        lctx = _lora_ctx(lora_pool, shard_ids,
                                         lora_tables)
                        ids_in = jnp.where(use_ov, override, ids)
                        logits, k, v = dec._ragged_logits(
                            weights, k, v, ids_in, pos, slots, rseq,
                            rctx, tables, lora=lctx)
                        toks = self._sample(logits, temps, key)
                        acc, k, v = dec._spec_accept(
                            k, v, toks, ids, slots, seg_start,
                            is_draft, scratch)
                        return toks, acc, k, v

                    if self.tp > 1:
                        self._spec_lora_j = jax.jit(
                            dec.tp_wrap(spec_lora_chunk, n_extra=13,
                                        outs="takv", lora_pool=True),
                            donate_argnums=(1, 2))
                    else:
                        self._spec_lora_j = jax.jit(
                            spec_lora_chunk, donate_argnums=(1, 2))

            if self.multi_step > 1:
                ms_scratch = self._scratch_slot

                def ragged_ms_chunk(weights, k, v, prev_toks, last_t,
                                    prev_col, use_host, override,
                                    ids_all, pos_all, slots_all,
                                    rseq_all, rctx_all, use_carry,
                                    tables, temps_all, keys, eos_ids):
                    """The fused k-step window (ISSUE 16): ragged_chunk
                    over k*T decode ministeps with ON-DEVICE EOS
                    bookkeeping. ``eos_ids`` [W] carries each column's
                    EOS token id (-1 = none); a per-column ``live``
                    mask rides the scan carry — once a column samples
                    its EOS, later iterations redirect its KV append
                    to the scratch slot (the write-neutralization
                    mechanism preemption already uses) and freeze its
                    carried token, so a finished column's remaining
                    ministeps are no-ops whose outputs the host
                    discards at the mid-chunk-EOS cut. The EOS token
                    itself IS delivered (the freeze applies from the
                    NEXT iteration), and its own KV never lands in
                    real pages — exactly the single-step schedule, so
                    greedy outputs are token-identical to
                    multi_step=1."""
                    first = jnp.where(use_host, override,
                                      prev_toks[last_t, prev_col])
                    live0 = jnp.ones(use_host.shape, bool)

                    def step(carry, xs):
                        cur, live, kp, vp = carry
                        ids_d, pos, slots, rseq, rctx, uc, temp, key \
                            = xs
                        ids = jnp.where(uc, cur, ids_d)
                        slots = jnp.where(live, slots, ms_scratch)
                        logits, kp, vp = dec._ragged_logits(
                            weights, kp, vp, ids, pos, slots, rseq,
                            rctx, tables)
                        nxt = self._sample(logits, temp, key)
                        nxt = jnp.where(live, nxt, cur)
                        live = live & (nxt != eos_ids)
                        return (nxt, live, kp, vp), nxt

                    (_, _, k, v), toks = jax.lax.scan(
                        step, (first, live0, k, v),
                        (ids_all, pos_all, slots_all, rseq_all,
                         rctx_all, use_carry, temps_all, keys))
                    return toks, k, v          # [k*T, W]

                def ragged_ms_chunk_rich(weights, k, v, prev_toks,
                                         last_t, prev_col, use_host,
                                         override, ids_all, pos_all,
                                         slots_all, rseq_all, rctx_all,
                                         use_carry, tables, temps_all,
                                         keys, eos_ids, top_ks_all,
                                         top_ps_all, reps_all, seen,
                                         upd, allowed):
                    """Per-request-sampling twin of the fused window:
                    the seen mask accumulates only while the column is
                    live (a frozen column's repeated carried token
                    must not re-mark itself — under multi_step=1 the
                    request retires before any such iteration runs)."""
                    first = jnp.where(use_host, override,
                                      prev_toks[last_t, prev_col])
                    live0 = jnp.ones(use_host.shape, bool)
                    w = use_host.shape[0]

                    def step(carry, xs):
                        cur, live, kp, vp, seen_c = carry
                        (ids_d, pos, slots, rseq, rctx, uc, temp, key,
                         tks, tps, rp) = xs
                        ids = jnp.where(uc, cur, ids_d)
                        slots = jnp.where(live, slots, ms_scratch)
                        logits, kp, vp = dec._ragged_logits(
                            weights, kp, vp, ids, pos, slots, rseq,
                            rctx, tables)
                        nxt = self._sample_rich(logits, temp, key, tks,
                                                tps, rp, seen_c,
                                                allowed)
                        nxt = jnp.where(live, nxt, cur)
                        rows = jnp.arange(w)
                        seen_c = seen_c.at[rows, nxt].set(
                            seen_c[rows, nxt] | (upd & live))
                        live = live & (nxt != eos_ids)
                        return (nxt, live, kp, vp, seen_c), nxt

                    (_, _, k, v, _), toks = jax.lax.scan(
                        step, (first, live0, k, v, seen),
                        (ids_all, pos_all, slots_all, rseq_all,
                         rctx_all, use_carry, temps_all, keys,
                         top_ks_all, top_ps_all, reps_all))
                    return toks, k, v          # [k*T, W]

                if self.tp > 1:
                    # tp_wrap'd like the base families: every operand
                    # past weights/k/v replicated, so tp=N multiplies
                    # the per-block collectives by EXACTLY k — pinned
                    # by comm_audit serving.ragged_k4_tp2
                    self._ragged_ms_j = jax.jit(
                        dec.tp_wrap(ragged_ms_chunk, n_extra=15),
                        donate_argnums=(1, 2))
                    self._ragged_ms_rich_j = jax.jit(
                        dec.tp_wrap(ragged_ms_chunk_rich, n_extra=21),
                        donate_argnums=(1, 2))
                else:
                    self._ragged_ms_j = jax.jit(
                        ragged_ms_chunk, donate_argnums=(1, 2))
                    self._ragged_ms_rich_j = jax.jit(
                        ragged_ms_chunk_rich, donate_argnums=(1, 2))

                if self.lora is not None:
                    def ragged_ms_lora_chunk(weights, k, v, lora_pool,
                                             shard_ids, lora_tables,
                                             prev_toks, last_t,
                                             prev_col, use_host,
                                             override, ids_all,
                                             pos_all, slots_all,
                                             rseq_all, rctx_all,
                                             use_carry, tables,
                                             temps_all, keys, eos_ids):
                        """ragged_ms_chunk with per-row LoRA deltas:
                        the adapter-page factors are gathered ONCE per
                        window (scan-invariant, PR 10's per-dispatch
                        state riding the fused scan)."""
                        lctx = _lora_ctx(lora_pool, shard_ids,
                                         lora_tables)
                        first = jnp.where(use_host, override,
                                          prev_toks[last_t, prev_col])
                        live0 = jnp.ones(use_host.shape, bool)

                        def step(carry, xs):
                            cur, live, kp, vp = carry
                            (ids_d, pos, slots, rseq, rctx, uc, temp,
                             key) = xs
                            ids = jnp.where(uc, cur, ids_d)
                            slots = jnp.where(live, slots, ms_scratch)
                            logits, kp, vp = dec._ragged_logits(
                                weights, kp, vp, ids, pos, slots,
                                rseq, rctx, tables, lora=lctx)
                            nxt = self._sample(logits, temp, key)
                            nxt = jnp.where(live, nxt, cur)
                            live = live & (nxt != eos_ids)
                            return (nxt, live, kp, vp), nxt

                        (_, _, k, v), toks = jax.lax.scan(
                            step, (first, live0, k, v),
                            (ids_all, pos_all, slots_all, rseq_all,
                             rctx_all, use_carry, temps_all, keys))
                        return toks, k, v          # [k*T, W]

                    def ragged_ms_lora_chunk_rich(weights, k, v,
                                                  lora_pool, shard_ids,
                                                  lora_tables,
                                                  prev_toks, last_t,
                                                  prev_col, use_host,
                                                  override, ids_all,
                                                  pos_all, slots_all,
                                                  rseq_all, rctx_all,
                                                  use_carry, tables,
                                                  temps_all, keys,
                                                  eos_ids, top_ks_all,
                                                  top_ps_all, reps_all,
                                                  seen, upd, allowed):
                        """ragged_ms_chunk_rich with per-row LoRA
                        deltas."""
                        lctx = _lora_ctx(lora_pool, shard_ids,
                                         lora_tables)
                        first = jnp.where(use_host, override,
                                          prev_toks[last_t, prev_col])
                        live0 = jnp.ones(use_host.shape, bool)
                        w = use_host.shape[0]

                        def step(carry, xs):
                            cur, live, kp, vp, seen_c = carry
                            (ids_d, pos, slots, rseq, rctx, uc, temp,
                             key, tks, tps, rp) = xs
                            ids = jnp.where(uc, cur, ids_d)
                            slots = jnp.where(live, slots, ms_scratch)
                            logits, kp, vp = dec._ragged_logits(
                                weights, kp, vp, ids, pos, slots,
                                rseq, rctx, tables, lora=lctx)
                            nxt = self._sample_rich(logits, temp, key,
                                                    tks, tps, rp,
                                                    seen_c, allowed)
                            nxt = jnp.where(live, nxt, cur)
                            rows = jnp.arange(w)
                            seen_c = seen_c.at[rows, nxt].set(
                                seen_c[rows, nxt] | (upd & live))
                            live = live & (nxt != eos_ids)
                            return (nxt, live, kp, vp, seen_c), nxt

                        (_, _, k, v, _), toks = jax.lax.scan(
                            step, (first, live0, k, v, seen),
                            (ids_all, pos_all, slots_all, rseq_all,
                             rctx_all, use_carry, temps_all, keys,
                             top_ks_all, top_ps_all, reps_all))
                        return toks, k, v          # [k*T, W]

                    if self.tp > 1:
                        self._ragged_ms_lora_j = jax.jit(
                            dec.tp_wrap(ragged_ms_lora_chunk,
                                        n_extra=16, lora_pool=True),
                            donate_argnums=(1, 2))
                        self._ragged_ms_lora_rich_j = jax.jit(
                            dec.tp_wrap(ragged_ms_lora_chunk_rich,
                                        n_extra=22, lora_pool=True),
                            donate_argnums=(1, 2))
                    else:
                        self._ragged_ms_lora_j = jax.jit(
                            ragged_ms_lora_chunk, donate_argnums=(1, 2))
                        self._ragged_ms_lora_rich_j = jax.jit(
                            ragged_ms_lora_chunk_rich,
                            donate_argnums=(1, 2))

        # -- program observatory: register every family (ISSUE 14) ----------
        # the registration order fixes the family names compile spans,
        # attribution histograms and trace_report tables use; `info`
        # carries the decoder's build fingerprint so a compile record
        # says WHICH decoder build it belongs to
        info = dict(getattr(dec, "program_build_info", {}) or {})
        info["tp"] = self.tp
        for fam, fn in self._program_families():
            self.compile_watch.register(fam, fn, **info)

    def _program_families(self):
        """(family name, jitted callable) for every serving program
        this engine can dispatch — the CompileWatch registration set
        AND the warmup_programs grid's family list."""
        fams = [("prefill", self._prefill_j),
                ("prefill_prefix", self._prefill_prefix_j),
                ("decode", self._decode_j),
                ("decode_rich", self._decode_rich_j),
                ("merge", self._merge_first_j)]
        if self._can_recompute:
            fams += [("prefill_mid", self._prefill_mid_j),
                     ("prefill_mid0", self._prefill_mid0_j)]
        if self.ragged:
            fams += [("ragged", self._ragged_j),
                     ("ragged_rich", self._ragged_rich_j)]
        if self.lora is not None:
            fams += [("ragged_lora", self._ragged_lora_j),
                     ("ragged_lora_rich", self._ragged_lora_rich_j)]
        if self.multi_step > 1:
            fams += [("ragged_ms", self._ragged_ms_j),
                     ("ragged_ms_rich", self._ragged_ms_rich_j)]
            if self.lora is not None:
                fams += [("ragged_ms_lora", self._ragged_ms_lora_j),
                         ("ragged_ms_lora_rich",
                          self._ragged_ms_lora_rich_j)]
        if self.spec is not None:
            fams.append(("spec", self._spec_j))
            if self.lora is not None:
                fams.append(("spec_lora", self._spec_lora_j))
        return fams

    def _sample(self, logits, temp, key):
        """In-program sampling: per-slot temperature (<=0 → greedy),
        engine-static top_k."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if self.top_k > 0:
            kth = jax.lax.top_k(logits, self.top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -1e30, logits)
        t = jnp.maximum(temp, 1e-6)[:, None]
        sampled = jax.random.categorical(
            key, logits / t, axis=-1).astype(jnp.int32)
        return jnp.where(temp > 0.0, sampled, greedy)

    def _sample_rich(self, logits, temp, key, top_ks, top_ps, rep,
                     seen, allowed=None):
        """Per-request sampling, all mask-based so one compiled program
        serves every parameter combination (models/generation.py:26-46
        semantics): repetition penalty over the seen mask, per-slot
        top_k via the k-th order statistic of the sorted logits,
        per-slot top_p nucleus over the tempered distribution.
        logits [b, V] f32; temp/top_ps/rep [b] f32; top_ks [b] i32;
        seen [b, V] bool; allowed [b, V] bool (the structured-decoding
        vocab restriction — applied BEFORE the greedy argmax and the
        filters, so constrained greedy is the argmax over the masked
        logits and sampling renormalizes inside the mask; an all-True
        row is the bitwise identity)."""
        v = logits.shape[-1]
        logits = logits.astype(jnp.float32)
        # repetition penalty (HF semantics: shrink positive logits,
        # amplify negative ones, only for already-seen tokens)
        pen = jnp.where(logits > 0, logits / rep[:, None],
                        logits * rep[:, None])
        logits = jnp.where(seen & (rep != 1.0)[:, None], pen, logits)
        if allowed is not None:
            logits = jnp.where(allowed, logits, -1e30)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lt = logits / jnp.maximum(temp, 1e-6)[:, None]
        # ONE descending sort serves both filters
        sorted_l = jnp.sort(lt, axis=-1)[..., ::-1]         # [b, V]
        # per-slot top_k: k-th largest value as the cutoff
        k_idx = jnp.clip(top_ks - 1, 0, v - 1)
        kth = jnp.take_along_axis(sorted_l, k_idx[:, None], axis=1)
        lt = jnp.where((top_ks > 0)[:, None] & (lt < kth), -1e30, lt)
        # per-slot top_p over the top_k-FILTERED distribution (the
        # generation.py order: top_k first, then nucleus). The filtered
        # sorted array is just the sorted prefix with ranks >= k masked,
        # so the single sort above still serves.
        rank = jnp.arange(v)[None, :]
        sorted_k = jnp.where(
            (top_ks > 0)[:, None] & (rank >= top_ks[:, None]),
            -1e30, sorted_l)
        probs = jax.nn.softmax(sorted_k, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff = cum - probs > top_ps[:, None]
        pth = jnp.where(cutoff, jnp.inf, sorted_k).min(
            axis=-1, keepdims=True)
        lt = jnp.where((top_ps < 1.0)[:, None] & (lt < pth), -1e30, lt)
        sampled = jax.random.categorical(key, lt, axis=-1) \
            .astype(jnp.int32)
        return jnp.where(temp > 0.0, sampled, greedy)

    def _next_key(self):
        self._key, k = jax.random.split(self._key)
        return k

    # -- telemetry (ISSUE 12) ------------------------------------------------
    # reservoir capacity for the finished-request ITL aggregation: big
    # enough that every existing test/bench workload stays EXACT (they
    # emit far fewer samples), small enough to bound unbounded runs
    ITL_RESERVOIR_K = 4096

    def set_telemetry(self, tracer, replica_id: int = 0):
        """Attach (tracer) or detach (None) serving telemetry. The
        tracer is shared down into the KV pool and the adapter registry
        so cache and adapter events ride the same flight recorder;
        ``replica_id`` becomes the pid every record of this engine
        carries (the fleet Router sets it to the replica index)."""
        self.tracer = tracer
        self.replica_id = int(replica_id)
        cache = self.dec.cache
        cache.tracer = tracer
        cache.trace_pid = self.replica_id
        if self.lora is not None:
            self.lora.tracer = tracer
            self.lora.trace_pid = self.replica_id
        # the compile watch shares the tracer's registry (compile
        # spans + compile.* counters land beside everything else);
        # without a tracer it keeps its own registry so sealed-set
        # detection still works untraced
        self.compile_watch.bind(tracer, pid=self.replica_id)

    def _profile_metrics(self):
        """Registry the sampled-attribution histograms feed: the
        tracer's when attached, else a private one (profiling without
        a tracer still measures — the engine just owns the registry)."""
        if self.tracer is not None:
            return self.tracer.metrics
        if self._prof_metrics is None:
            from ..utils.telemetry import MetricsRegistry
            self._prof_metrics = MetricsRegistry()
        return self._prof_metrics

    def _prof_due(self) -> bool:
        """Deterministic every-Nth sampling with a seeded start phase
        (profile_seed): identical runs fence identical dispatches."""
        if not self._prof_n:
            return False
        self._prof_countdown -= 1
        if self._prof_countdown > 0:
            return False
        self._prof_countdown = self._prof_n
        return True

    def _slo_attrs(self, req: Request) -> dict:
        return {"adapter_id": req.sampling.adapter_id,
                "priority": req.sampling.priority}

    def _slo_ttft(self, req: Request, now: float):
        """Feed the request's TTFT into the SLO windows (call sites
        guard on self._slo; all three first-token paths route here)."""
        self._slo.observe("ttft", now - req.t_submit,
                          self._slo_attrs(req), now=now)

    def _mark_first_token(self, req: Request, now: float):
        """First-token bookkeeping shared by the dense/ragged/spec
        prefill-final collection paths — first LIFE only: a
        preemption-recompute re-entry is not a first token and must
        not overwrite the true ttft_s or feed an inflated sample into
        the SLO windows."""
        if req.t_first_token is None:
            req.t_first_token = now
            if self._slo is not None:
                self._slo_ttft(req, now)
        req.t_last_emit = now

    def _prof_record(self, kind: str, fn, host_s: float, queue_s: float,
                     execute_s: float):
        """Record one sampled dispatch attribution: host-schedule
        (since the previous device call ended — admission + schedule
        building), dispatch-queue (draining previously enqueued work)
        and device-execute (this program's own wall), overall and per
        program family."""
        family = self.compile_watch.family_of(fn) \
            or kind.split(":", 1)[-1]
        m = self._profile_metrics()
        m.histogram("profile.host_schedule_s").observe(max(0.0, host_s))
        m.histogram("profile.dispatch_queue_s").observe(
            max(0.0, queue_s))
        m.histogram("profile.device_execute_s").observe(
            max(0.0, execute_s))
        m.histogram(f"profile.device_execute_s.{family}").observe(
            max(0.0, execute_s))
        self.profiled_dispatches += 1
        if self.tracer is not None:
            self.tracer.event(
                "profile_sample", pid=self.replica_id, family=family,
                kind=kind, host_s=round(host_s, 6),
                queue_s=round(queue_s, 6),
                execute_s=round(execute_s, 6))

    def _trace_running(self, req: Request, now: float):
        """Close the current life's prefill span at the prefilling →
        running transition (call sites guard on self.tracer)."""
        if req.trace_id is None:
            return
        t0 = req.t_life or req.t_admit or now
        self.tracer.span(
            "prefill", req.trace_id, t0, now, pid=self.replica_id,
            epoch=req.epoch, n_cached=int(req.n_cached),
            recompute=bool(req.resume))
        req.t_run = now

    def _trace_life_end(self, req: Request, reason: str, now: float):
        """Close whatever phase span the current life was in — decode
        for a running request, prefill (interrupted) for a prefilling
        one, queued for one that never got a slot — and reset the
        per-life markers (call sites guard on self.tracer)."""
        if req.trace_id is None:
            return
        tr = self.tracer
        if req.t_run is not None:
            tr.span("decode", req.trace_id, req.t_run, now,
                    pid=self.replica_id, epoch=req.epoch, reason=reason,
                    tokens=len(req.out_tokens))
        elif req.t_life:
            tr.span("prefill", req.trace_id, req.t_life, now,
                    pid=self.replica_id, epoch=req.epoch, reason=reason,
                    interrupted=True)
        elif req.t_queued:
            tr.span("queued", req.trace_id, req.t_queued, now,
                    pid=self.replica_id, reason=reason)
        if req.t_wait is not None:
            tr.span("splice_wait", req.trace_id, req.t_wait, now,
                    pid=self.replica_id, reason=reason)
        req.t_run = None
        req.t_life = 0.0
        req.t_wait = None

    # -- fault tolerance -----------------------------------------------------
    def _device_call(self, kind: str, fn, *args):
        """Every device dispatch/fetch routes through here: the chaos
        injection point plus bounded retry with exponential backoff.
        A transient error (injected or a flaky device/link) re-invokes
        the SAME call — args unchanged, PRNG key already baked in, so a
        successful retry is token-identical to a clean first try.
        Allocator exhaustion passes straight through (it is handled by
        preemption, not retry); anything else that survives the retry
        budget surfaces as _DispatchFailed for the call site to turn
        into structured per-request failures.

        Caveat: a REAL device error raised after the runtime consumed
        a donated pool buffer can leave cache.k/v unusable — the engine
        then fails subsequent requests too, but never raises out of
        step(). The chaos harness always injects BEFORE the underlying
        call, so injected faults are guaranteed retry-safe."""
        attempt = 0
        dispatch = kind.startswith("dispatch:")
        # sampled dispatch-time attribution (ISSUE 14): decided ONCE
        # per logical call (not per retry) so the seeded cadence is
        # schedule-stable; the fences run on the attempt that succeeds
        prof = dispatch and self._prof_due()
        while True:
            try:
                if self.chaos is not None:
                    self.chaos.before_call(self, kind)
                if prof:
                    tq0 = time.perf_counter()
                    host_s = tq0 - self._prof_mark
                    prev = (self._inflight[-1]["toks"]
                            if self._inflight else None)
                    if prev is not None:
                        # drain the device queue so the post-dispatch
                        # fence times THIS program, not its backlog —
                        # the sampled profiling mode's designed sync
                        jax.block_until_ready(prev)  # flightcheck: disable=FC301
                    tq1 = time.perf_counter()
                t0 = time.perf_counter() if dispatch else 0.0
                out = fn(*args)
                t1 = time.perf_counter() if dispatch else 0.0
                if prof:
                    # the sampled fence: device-execute wall of this
                    # program alone (queue drained above). Values are
                    # unchanged — block_until_ready never rewrites —
                    # so tokens stay bitwise identical, sampled or not
                    jax.block_until_ready(out)  # flightcheck: disable=FC301
                    self._prof_record(kind, fn, host_s, tq1 - tq0,
                                      time.perf_counter() - t1)
                    prof = False
                if dispatch:
                    n_new, n_unexp = self.compile_watch.observe(
                        fn, t0, t1, args)
                    if n_new:
                        self.program_compiles += n_new
                    if n_unexp:
                        self.unexpected_recompiles += n_unexp
                    # every successful device-program launch (prefill /
                    # decode / merge / ragged) — the denominator of
                    # stats()["tokens_per_dispatch"]
                    self.device_dispatches += 1
                if self._prof_n:
                    self._prof_mark = time.perf_counter()
                return out
            except KVCacheExhausted:
                raise
            except Exception as e:          # noqa: BLE001 — fault wall
                if attempt >= self.max_dispatch_retries:
                    self.dispatch_exhaustions += 1
                    if self.tracer is not None:
                        self.tracer.event(
                            "dispatch_exhausted", pid=self.replica_id,
                            kind=kind, error=type(e).__name__)
                    raise _DispatchFailed(kind, e) from e
                attempt += 1
                self.retries += 1
                if self.tracer is not None:
                    self.tracer.event("retry", pid=self.replica_id,
                                      kind=kind, attempt=attempt)
                if self.retry_backoff_s > 0:
                    time.sleep(self.retry_backoff_s
                               * (2 ** (attempt - 1)))

    def cancel(self, req_id: int) -> bool:
        """Explicitly abort a request in ANY live state: queued (just
        dequeued), prefilling (allocation unwound — splice-pending
        hashes invalidated, dependent readers restarted, blocks freed)
        or running (partial tokens kept; pages freed once no in-flight
        chunk references them). Returns False if the request is already
        terminal; raises KeyError for an unknown id."""
        req = self._find_request(req_id)
        if req is None:
            raise KeyError(f"unknown req_id {req_id}")
        if req.state in ("done", "aborted", "failed"):
            return False
        self._abort_request(req, "cancelled")
        return True

    def _find_request(self, req_id: int) -> Optional[Request]:
        if req_id in self._done:
            return self._done[req_id]
        for r in self._slots:
            if r is not None and r.req_id == req_id:
                return r
        for r in self._queue:
            if r.req_id == req_id:
                return r
        return None

    def _enforce_deadlines(self):
        """Abort every live request past its wall-clock deadline (the
        terminal state is ABORTED with error='deadline...'; partial
        tokens are kept — a caller that can use a truncated answer
        still gets one)."""
        now = time.perf_counter()
        expired = [r for r in list(self._queue)
                   + [s for s in self._slots if s is not None]
                   if r.deadline_at is not None and now > r.deadline_at]
        for req in expired:
            self.deadline_misses += 1
            self._abort_request(
                req, f"deadline exceeded "
                     f"({req.sampling.deadline_s:.3f}s budget)")

    def _estimate_completion_s(self, sp: SamplingParams
                               ) -> Optional[float]:
        """Admission-time completion estimate for overload shedding:
        backlog tokens (queued + running remainders + the candidate's
        own budget) over the engine's measured aggregate token rate.
        None until the engine has produced enough traffic to have a
        rate — cold engines never shed on deadline math."""
        busy = self.time_prefill_s + self.time_stall_s + self.time_host_s
        if self.generated_tokens < 8 or busy <= 0:
            return None
        rate = self.generated_tokens / busy
        backlog = sum(r.sampling.max_new_tokens - len(r.out_tokens)
                      for r in self._queue)
        backlog += sum(r.sampling.max_new_tokens - len(r.out_tokens)
                       for r in self._slots if r is not None)
        return (backlog + sp.max_new_tokens) / rate

    def _pick_victim(self, exclude=()) -> Optional[Request]:
        """Preemption victim under KV pressure: lowest priority first,
        newest req_id on ties — so the oldest highest-priority request
        always makes progress (no preemption livelock). Running
        requests are preferred victims (their blocks free the most);
        prefilling ones only when no running victim exists."""
        if not self._can_recompute:
            return None
        for states in (("running",), ("prefilling",)):
            cands = [r for r in self._slots
                     if r is not None and r.state in states
                     and r not in exclude]
            if cands:
                return max(cands, key=lambda r: (-r.sampling.priority,
                                                 r.req_id))
        return None

    def _preempt(self, victim: Request):
        """Preemption-with-recompute: evict `victim` from its slot,
        free its blocks back to the pool NOW (safe: any in-flight chunk
        touching them was dispatched earlier, and device program order
        runs it before any later program that could reuse the pages;
        collection drops the victim's in-flight tokens via the epoch
        guard), and re-enqueue it at the queue front. A RUNNING victim
        resumes by re-prefilling prompt ++ generated history through
        the no-sample chunk programs — full prompt blocks usually park
        in the prefix-cache LRU at free and splice straight back in,
        so recompute cost is near zero on hits. A PREFILLING victim
        restarts its prefill from scratch."""
        self.preemptions += 1
        if self.tracer is not None and victim.trace_id is not None:
            self.tracer.event(
                "preempt", trace=victim.trace_id, pid=self.replica_id,
                state=victim.state, tokens=len(victim.out_tokens),
                priority=victim.sampling.priority)
        self._evict_to_queue(victim)
        self._requeue_front([victim])

    def _evict_to_queue(self, req: Request):
        """Evict a live slotted request back to a fresh queued life:
        bump the epoch (collection drops the old life's in-flight
        tokens), vacate the slot, unwind/free the old allocation, and
        reset all per-life prefill progress. The unwind runs while the
        old coverage (n_cached/prefill_sent/deps) is still intact —
        a RUNNING request's fully-dispatched prefill lets reader deps
        prune as met BEFORE the reset below could spuriously re-arm
        them against the next life. The free is always IMMEDIATE (safe
        by device program order: every in-flight chunk touching the
        pages was dispatched earlier) — deferring it to collection
        while the request re-enters the queue would let the next
        _admit re-allocate its seq before the free lands and raise out
        of step(). The caller requeues."""
        if self.tracer is not None and req.trace_id is not None:
            now = time.perf_counter()
            self._trace_life_end(req, "evict", now)
            req.t_queued = now      # the requeued life's queued span
        req.epoch += 1
        si = req.slot
        if si is not None:
            self._slots[si] = None
            self._fresh_slots.discard(si)
        req.slot = None
        # adapter pin travels with the slot: the evicted life's pages
        # park (evictable — "an adapter eviction preempts like a KV
        # OOM"); re-admission re-acquires, reviving or refaulting
        self._lora_release(req)
        if req.state == "prefilling":
            self._unwind_alloc(req, immediate=True)
        else:
            self._restart_dependent_readers(req)
            self.dec.cache.free(req.req_id)
        req.resume = bool(req.out_tokens)
        req.state = "queued"
        req.planned = len(req.out_tokens)
        req.n_cached = 0
        req.prefill_sent = 0
        req.deps = []
        req.pending_blocks = []
        req.ctx = None

    def _extend_with_preempt(self, req: Request, exclude=()) -> int:
        """cache.extend with pressure relief: on exhaustion, preempt
        the policy victim (lowest priority first, newest on ties —
        see _pick_victim; no age constraint relative to `req` itself)
        and retry. `req` stays in the victim pool — when IT is the
        chosen victim the exhaustion propagates and the caller FAILS
        `req` (both callers, _dispatch_mid and _dispatch_final,
        convert it to a terminal failed state)."""
        while True:
            try:
                return self.dec.cache.extend(req.req_id)
            except KVCacheExhausted:
                victim = self._pick_victim(exclude=tuple(exclude))
                if victim is None or victim is req:
                    raise
                self._preempt(victim)

    def _requeue_front(self, reqs: Sequence[Request]):
        """Put preempted/restarted requests back into the queue in
        global req_id order. Arrivals enter the queue in req_id order,
        so re-sorting the whole queue keeps FIFO fairness while placing
        every evicted request ahead of anything that arrived after it —
        including requests requeued by EARLIER calls (a blind
        front-prepend would let a newer victim jump an older restarted
        request and starve it under sustained pressure)."""
        if not reqs:
            return
        merged = sorted(list(self._queue) + list(reqs),
                        key=lambda r: r.req_id)
        self._queue.clear()
        self._queue.extend(merged)

    def _unwind_alloc(self, req: Request, immediate: bool = False):
        """Safely unwind a PREFILLING request's allocation:
        1. invalidate hash registrations of its own full prefill blocks
           whose covering chunk was never dispatched (their registered
           content will never exist — a later splice would read junk);
        2. drop its splice-pending writer entries;
        3. restart any reader still waiting on those unwritten blocks
           (the reader spliced physical blocks this request will now
           never write — its allocation is unwound recursively and it
           re-enters the queue);
        4. free the blocks (immediately for preemption — the caller
           needs them NOW; otherwise after the newest in-flight chunk,
           like _retire)."""
        cache = self.dec.cache
        bs = cache.block_size
        covered = req.n_cached + req.prefill_sent
        try:
            table = cache.seq_blocks(req.req_id)
        except KeyError:
            table = None
        if table is not None:
            own_uncovered = [
                table[j]
                for j in range(req.n_cached // bs,
                               len(req.prefill_tokens) // bs)
                if (j + 1) * bs > covered and j < len(table)]
            cache.unregister_block_hashes(own_uncovered)
        self._clear_pending_writes(req)
        self._restart_dependent_readers(req)
        if table is not None:
            if immediate or not self._inflight:
                cache.free(req.req_id)
            else:
                self._inflight[-1]["free_after"].append(req.req_id)

    def _restart_dependent_readers(self, writer: Request):
        """Resolve every splice dependency on `writer` against its
        CURRENT dispatch coverage, BEFORE that coverage is rolled back
        by preemption/unwind: met deps reference chunks that were
        really dispatched and will execute regardless of what happens
        to the writer now — they are PRUNED here (left in place, a met
        dep would spuriously re-arm against the writer's next life,
        whose prefill_sent restarts at 0 with different blocks and a
        possibly shorter suffix — the reader would stall forever).
        Readers with UNMET deps spliced blocks the writer will now
        never write; they restart from scratch."""
        for r in self._slots:
            if r is not None and r.deps:
                r.deps = [(w, need) for w, need in r.deps
                          if not (w is writer
                                  and writer.prefill_sent >= need)]
        readers = [r for r in self._slots
                   if r is not None and r.state == "prefilling"
                   and any(w is writer for w, need in r.deps)]
        restarted = []
        for r in readers:
            # the recursive unwind below may already have restarted a
            # later snapshot entry (a reader depending on BOTH this
            # writer and r) — evicting it twice would double-enqueue it
            if r.state != "prefilling":
                continue
            self._evict_to_queue(r)      # recursive: r may have readers
            restarted.append(r)
        self._requeue_front(restarted)

    def _abort_request(self, req: Request, msg: str):
        self.aborted += 1
        self._finalize(req, "aborted", msg)

    def _fail_request(self, req: Request, msg: str):
        self.failed += 1
        self._finalize(req, "failed", msg)

    def _finalize(self, req: Request, state: str, msg: str):
        """Move a live request to a terminal fault state, unwinding
        whatever stage it was in. Partial tokens are kept; `error`
        records why."""
        if req.state == "queued":
            try:
                self._queue.remove(req)
            except ValueError:
                pass
        else:
            si = req.slot
            if si is not None:
                self._slots[si] = None
                self._fresh_slots.discard(si)
            req.slot = None
            req.epoch += 1     # in-flight chunks must drop its tokens
            self._lora_release(req)
            if req.state == "prefilling":
                self._unwind_alloc(req)
            elif req.req_id in self.dec.cache._tables:
                # running: pages freed after the newest in-flight chunk
                # (it was dispatched assuming continuation), like
                # _retire
                if self._inflight:
                    self._inflight[-1]["free_after"].append(req.req_id)
                else:
                    self.dec.cache.free(req.req_id)
        req.state = state
        req.error = msg
        req.t_done = time.perf_counter()
        if self.tracer is not None and req.trace_id is not None:
            self._trace_life_end(req, state, req.t_done)
            if not req.trace_keep_open:
                self.tracer.end_request(
                    req.trace_id, state, replica=self.replica_id,
                    error=msg)
        self._done[req.req_id] = req

    def debug_dump(self) -> str:
        """One human-readable snapshot of the scheduler — per-request
        states, queue/pipeline depth, robustness counters and cache
        occupancy. The watchdog appends this to its hang report."""
        cache = self.dec.cache
        lines = ["serving engine state:"]
        for si, r in enumerate(self._slots):
            if r is None:
                lines.append(f"  slot {si}: idle")
            else:
                lines.append(
                    f"  slot {si}: req {r.req_id} state={r.state} "
                    f"out={len(r.out_tokens)}/{r.sampling.max_new_tokens}"
                    f" planned={r.planned} prefill={r.prefill_sent}/"
                    f"{r.suffix_len} epoch={r.epoch} resume={r.resume}")
        lines.append(f"  queue depth={len(self._queue)} ids="
                     f"{[r.req_id for r in self._queue][:16]}")
        lines.append(f"  inflight={len(self._inflight)} "
                     f"finished={len(self._done)}")
        lines.append(
            f"  counters: preemptions={self.preemptions} "
            f"retries={self.retries} aborted={self.aborted} "
            f"failed={self.failed} deadline_misses={self.deadline_misses}"
            f" shed={self.shed_requests} "
            f"recompute_tokens={self.recompute_tokens}")
        lines.append(
            f"  cache: free_blocks={cache.free_blocks} "
            f"cached_blocks={cache.cached_blocks} "
            f"referenced={len(cache._ref)} of {cache.num_blocks}")
        return "\n".join(lines) + "\n"

    # -- public API ----------------------------------------------------------
    def _validate_new_request(self, prompt, sp: SamplingParams):
        """Shared admission validation (add_request and the fleet
        migration path adopt_request): prompt normalization, bucket and
        pool-geometry checks, adapter registration, allowed-tokens mask
        normalization. Returns (prompt, allowed_mask). Raises on
        impossible geometry — validation, NOT shedding (the overload
        checks live in add_request only: a migrated request was already
        admitted to the fleet once and must not be shed at drain)."""
        prompt = _normalize_prompt(prompt)
        _bucket_for(int(prompt.size), self.buckets)  # validates length
        cache = self.dec.cache
        need = -(-(int(prompt.size) + sp.max_new_tokens)
                 // cache.block_size)
        # a tenant request must fit its KV *plus* its adapter's pages
        # (both come out of the same pool) — reject impossible
        # geometry at the door, like oversized prompts
        lora_pages = 0
        if sp.adapter_id is not None:
            if self.lora is None:
                raise ValueError(
                    f"adapter_id={sp.adapter_id!r} but the engine has "
                    f"no AdapterRegistry (pass lora= to ServingEngine)")
            if not self.lora.is_registered(sp.adapter_id):
                raise KeyError(
                    f"unknown adapter {sp.adapter_id!r} — register it "
                    f"before submitting requests")
            lora_pages = self.lora.n_pages()
        if need + lora_pages > cache.num_blocks - 1:  # -1: scratch page
            raise ValueError(
                f"request needs {need} KV pages"
                + (f" + {lora_pages} adapter pages" if lora_pages
                   else "")
                + f" but the pool only has {cache.num_blocks - 1}; "
                "shrink max_new_tokens/prompt or grow num_blocks")
        allowed_mask = None
        if sp.allowed_tokens is not None:
            allowed_mask = self._normalize_allowed(
                sp.allowed_tokens, self.dec.cfg.vocab_size)
        return prompt, allowed_mask

    def add_request(self, prompt, sampling: Optional[SamplingParams] = None
                    ) -> int:
        """Queue a prompt ([len] ids; list/np/Tensor). Returns req_id."""
        sp = sampling or SamplingParams()
        prompt, allowed_mask = self._validate_new_request(prompt, sp)
        # overload shedding: reject at the door what cannot be served —
        # a hard queue-depth cap, and (for deadline'd requests, once the
        # engine has a measured token rate) a backlog/deadline estimate
        if self.max_queue_depth is not None and \
                len(self._queue) >= self.max_queue_depth:
            self.shed_requests += 1
            if self.tracer is not None:
                self.tracer.event("shed", pid=self.replica_id,
                                  reason="queue_depth")
            raise EngineOverloaded(
                f"queue depth {len(self._queue)} at the "
                f"max_queue_depth={self.max_queue_depth} cap")
        if sp.deadline_s is not None:
            est = self._estimate_completion_s(sp)
            if est is not None and est > sp.deadline_s:
                self.shed_requests += 1
                if self.tracer is not None:
                    self.tracer.event("shed", pid=self.replica_id,
                                      reason="deadline_estimate")
                raise EngineOverloaded(
                    f"estimated completion {est:.3f}s exceeds the "
                    f"{sp.deadline_s:.3f}s deadline "
                    f"(backlog {len(self._queue)} queued)")
        rid = next(self._ids)
        req = Request(rid, prompt, sp, t_submit=time.perf_counter())
        req.allowed_mask = allowed_mask
        req.t_queued = req.t_submit
        if self.tracer is not None:
            req.trace_id = self.tracer.begin_request(
                rid, tenant=sp.adapter_id, replica=self.replica_id,
                prompt_len=int(prompt.size),
                max_new_tokens=sp.max_new_tokens)
        self._queue.append(req)
        return rid

    def adopt_request(self, prompt, sampling: Optional[SamplingParams]
                      = None, out_tokens: Sequence[int] = (),
                      t_submit: Optional[float] = None,
                      trace_id: Optional[int] = None) -> int:
        """Admit a request that already ran (partially) on ANOTHER
        engine — the fleet Router's replica-failover migration path
        (inference/fleet.py). The generated history re-enters this
        engine's pool through the preemption-recompute machinery
        (resume=True): the prefill reads prompt ++ out_tokens[:-1]
        through the NO-SAMPLE chunk programs — no PRNG key is drawn,
        the engine's key stream is untouched — and decode resumes from
        out_tokens[-1], so greedy outputs are token-identical across
        the migration. Overload shedding is BYPASSED (the fleet already
        admitted this request once; shedding a drain would drop it) —
        pool-geometry validation still applies. ``t_submit`` preserves
        the original submit time so deadlines keep their meaning on the
        new engine. A history that already satisfies the stop condition
        (budget spent / trailing EOS) completes immediately; an engine
        without the chunk programs drops the history and re-runs from
        the prompt (still greedy-identical, just more recompute).
        ``trace_id`` continues an existing telemetry span (the Router
        passes the migrating request's id, so the whole lifecycle stays
        ONE continuous span across replicas; None opens a fresh one
        when a tracer is attached)."""
        sp = sampling or SamplingParams()
        prompt, allowed_mask = self._validate_new_request(prompt, sp)
        rid = next(self._ids)
        req = Request(rid, prompt, sp,
                      t_submit=(time.perf_counter() if t_submit is None
                                else float(t_submit)))
        req.allowed_mask = allowed_mask
        req.t_queued = time.perf_counter()
        if self.tracer is not None:
            req.trace_id = (int(trace_id) if trace_id is not None
                            else self.tracer.begin_request(
                                rid, tenant=sp.adapter_id,
                                replica=self.replica_id,
                                prompt_len=int(prompt.size)))
            self.tracer.event(
                "adopt", trace=req.trace_id, pid=self.replica_id,
                history=len(out_tokens), req_id=rid)
        toks = [int(t) for t in out_tokens]
        if toks and not self._can_recompute:
            # no no-sample chunk programs: the history cannot re-enter
            # the pool without drawing keys — from-scratch re-prefill
            toks = []
        req.out_tokens = toks
        if toks and (len(toks) >= sp.max_new_tokens
                     or (sp.eos_token_id is not None
                         and toks[-1] == sp.eos_token_id)):
            # the migrated history already finished the request — a
            # resume admission would schedule one decode row past the
            # budget before retiring; complete it here instead
            req.out_tokens = toks[:sp.max_new_tokens]
            req.state = "done"
            req.t_done = time.perf_counter()
            if self.tracer is not None:
                self.tracer.end_request(
                    req.trace_id, "done", replica=self.replica_id,
                    tokens=len(req.out_tokens))
            self._done[rid] = req
            return rid
        req.resume = bool(toks)
        req.planned = len(toks)
        self._queue.append(req)
        return rid

    def result(self, req_id: int) -> np.ndarray:
        """Generated tokens (prompt excluded) of a terminal request.
        For aborted/failed requests this is the PARTIAL output produced
        before the fault — check request(req_id).state / .error."""
        req = self._done[req_id]
        return np.asarray(req.out_tokens, np.int32)

    def request(self, req_id: int) -> Request:
        """The terminal Request record (state is one of done | aborted
        | failed; error says why for the fault states)."""
        return self._done[req_id]

    @property
    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._inflight)
                or any(r is not None for r in self._slots))

    # -- scheduler -----------------------------------------------------------
    def _admit(self):
        """Claim free batch slots for queued requests. Admission is
        capacity-aware (a request enters only if its whole worst-case
        page demand fits — net of prefix-cache reuse — so a running
        request can never hit pool exhaustion mid-prefill or
        mid-decode) and NON-BLOCKING: it allocates pages and puts the
        request in the "prefilling" state; the actual prefill chunks
        are dispatched by _dispatch_prefill and their results fetched
        at collection time, like decode chunks.

        Prefix caching splices matched blocks at allocation time. A
        matched block may belong to a request that is still mid-prefill
        (its suffix's full prompt blocks register in the hash index at
        allocation, before any write is dispatched): the reader records
        (writer, suffix-tokens-needed) dependencies and its chunks hold
        back until the writer's covering dispatch has been issued —
        on-device program order then guarantees the reader sees the
        writer's pages."""
        cache = self.dec.cache
        for si in range(self.max_b):
            if self._slots[si] is not None:
                continue
            if not self._queue:
                break
            req = self._queue[0]
            # resume (preempted while running): the prefill source is
            # prompt ++ generated history minus the last token — the
            # history re-enters the pool via no-sample chunks and
            # decode resumes from out_tokens[-1]. Fresh admissions
            # prefill the prompt as before.
            if req.resume and req.out_tokens:
                toks = np.concatenate(
                    [req.prompt,
                     np.asarray(req.out_tokens[:-1], np.int32)])
            else:
                toks = req.prompt
            # pages reserved up front: everything (worst_case — a
            # running request can never exhaust the pool) or just the
            # prefill + one decode slot (optimistic — oversubscribes
            # the pool; pressure is relieved by preemption)
            if self.admission == "optimistic":
                total = int(len(toks)) + 1
            else:
                total = int(req.prompt.size) + req.sampling.max_new_tokens
            # adapter fault-in FIRST (ISSUE 10): its pages come out of
            # the same pool the KV allocation below draws from, so the
            # two claims must be ordered and individually unwound — an
            # adapter that cannot fault in waits at the queue head
            # exactly like a KV refusal (preemptions and frees
            # downstream relieve both)
            if req.sampling.adapter_id is not None:
                try:
                    self._lora_acquire(req)
                except KVCacheExhausted:
                    break  # head-of-line: keep FIFO, wait for frees
            if self.prefix_caching:
                try:
                    # one hash walk: the capacity check happens inside
                    # allocate_with_prefix BEFORE any mutation, so a
                    # refusal leaves the pool untouched. The chain is
                    # SALTED with the adapter id: a tenant's blocks
                    # hold its adapter's K/V and must never splice
                    # into another tenant's (or the base model's)
                    # table
                    reused, n_cached = cache.allocate_with_prefix(
                        req.req_id, toks, total,
                        salt=req.sampling.adapter_id)
                except RuntimeError:
                    # keep FIFO; drop the adapter pin taken above (the
                    # adapter stays parked-resident, so the retry next
                    # step is a cheap revive)
                    self._lora_release(req)
                    break
                req.deps = [self._pending_writes[b] for b in reused
                            if b in self._pending_writes]
                # register OUR fresh full prefill blocks as splice-
                # pending until our dispatches cover them
                table = cache.seq_blocks(req.req_id)
                bs = cache.block_size
                n_full = int(len(toks)) // bs
                for j in range(len(reused), n_full):
                    self._pending_writes[table[j]] = \
                        (req, (j + 1) * bs - n_cached)
                    req.pending_blocks.append(table[j])
            else:
                if cache.free_blocks < -(-total // cache.block_size):
                    self._lora_release(req)
                    break
                try:
                    cache.allocate(req.req_id, total)
                except RuntimeError:
                    self._lora_release(req)
                    break
                n_cached = 0
            self._queue.popleft()
            req.ctx = toks if req.resume else None
            req.n_cached = n_cached
            req.state = "prefilling"
            req.slot = si
            now = time.perf_counter()
            if req.t_admit is None:
                req.t_admit = now
            if self.tracer is not None and req.trace_id is not None:
                self.tracer.span(
                    "queued", req.trace_id, req.t_queued or now, now,
                    pid=self.replica_id, epoch=req.epoch,
                    resume=bool(req.resume))
                self.tracer.event(
                    "admitted", trace=req.trace_id,
                    pid=self.replica_id, slot=si,
                    n_cached=int(n_cached), resume=bool(req.resume))
                req.t_life = now
            if req.resume:
                # tokens that must genuinely recompute (past the splice)
                self.recompute_tokens += req.suffix_len
            self._slots[si] = req

    def _deps_ready(self, req: Request) -> bool:
        """True when every splice-pending writer has dispatched the
        chunks covering the blocks `req` spliced. Satisfied entries are
        PRUNED on the spot: a dispatched chunk executes no matter what
        later happens to its writer, but a preempted writer's
        prefill_sent rolls back to 0 — without pruning, a met
        dependency could spuriously re-arm against the writer's next
        life (whose blocks are different anyway)."""
        if req.deps:
            req.deps = [(w, need) for w, need in req.deps
                        if w.prefill_sent < need]
        if not req.deps:
            if req.t_wait is not None:
                # splice-wait over: the reader held its chunks back
                # for this long waiting on the writer's dispatches
                if self.tracer is not None and req.trace_id is not None:
                    self.tracer.span(
                        "splice_wait", req.trace_id, req.t_wait,
                        time.perf_counter(), pid=self.replica_id)
                req.t_wait = None
            return True
        if self.tracer is not None and req.t_wait is None:
            req.t_wait = time.perf_counter()
        return False

    def _clear_pending_writes(self, req: Request):
        for b in req.pending_blocks:
            if self._pending_writes.get(b, (None, 0))[0] is req:
                del self._pending_writes[b]
        req.pending_blocks = []

    def _dispatch_prefill(self):
        """Dispatch prefill work for prefilling slots, oldest request
        first (FIFO completes the earliest prompt soonest, which
        minimizes its TTFT and resolves splice dependencies in
        admission order). While decodes are running the dispatched
        tokens are capped at prefill_budget per step — the bound on
        how much prefill can slot between two decode chunks; an idle
        engine dispatches everything ready. Suffixes longer than
        prefill_chunk go out as width-1 fixed-size chunks (no-sample
        programs); each request's last dispatch is its bucketed,
        sampling "final" — grouped across requests per bucket exactly
        like monolithic admission prefills."""
        pending = sorted((r for r in self._slots
                          if r is not None and r.state == "prefilling"
                          and r.prefill_sent < r.suffix_len),
                         key=lambda r: r.req_id)
        if not pending:
            return
        decoding = any(r is not None and r.state == "running"
                       for r in self._slots)
        budget = self.prefill_budget if (decoding and
                                         self.prefill_budget) else None
        def _is_mid(r):
            # a preemption resume runs EVERY chunk through the
            # no-sample mid program (its "first token" is already
            # known — re-sampling would both corrupt the request and
            # shift the engine's PRNG stream for everyone else)
            if r.resume:
                return True
            return (self.prefill_chunk and
                    r.suffix_len - r.prefill_sent > self.prefill_chunk)

        spent = 0
        while True:
            ready = [r for r in pending
                     if r.state == "prefilling" and r.slot is not None
                     and r.prefill_sent < r.suffix_len
                     and self._deps_ready(r)]
            if not ready:
                return
            # strict FIFO: the OLDEST ready request's next dispatch goes
            # first — a newer long prompt's chunks must never starve an
            # older short request's final
            head = ready[0]
            if _is_mid(head):
                spent += self._dispatch_mid(head)
                if budget is not None and spent >= budget:
                    return
                continue
            # head's remainder fits one dispatch: group every ready
            # same-bucket final with it (equal priority, shared
            # program), closing a sub-group early when it crosses the
            # remaining budget — so at most ~budget + one row's suffix
            # ever slots between two decode chunks, not a whole
            # width-PREFILL_GROUP burst
            bucket = _bucket_for(head.suffix_len - head.prefill_sent,
                                 self.buckets)
            group = [(r.slot, r, r.n_cached + r.prefill_sent)
                     for r in ready if not _is_mid(r)
                     and _bucket_for(r.suffix_len - r.prefill_sent,
                                     self.buckets) == bucket]
            w = min(self.PREFILL_GROUP, self.max_b) \
                if len(group) > 1 else 1
            sub, toks = [], 0
            for row in group:
                if row[1].state != "prefilling" or row[1].slot is None:
                    # an EARLIER sub's (injected) KV exhaustion picked
                    # this row's request as the preemption victim —
                    # its seq is freed and the row is stale; it will
                    # re-enter through the queue
                    continue
                sub.append(row)
                toks += int(row[1].prompt.size) - row[2]
                if len(sub) == w or (budget is not None
                                     and spent + toks >= budget):
                    self._dispatch_final(bucket, sub, w)
                    spent += toks
                    sub, toks = [], 0
                    if budget is not None and spent >= budget:
                        return
            if sub:
                self._dispatch_final(bucket, sub, w)
                spent += toks
                if budget is not None and spent >= budget:
                    return

    # prefill dispatch widths: exactly TWO compile variants per bucket
    # (a variant per group size would compile-storm on bursty arrivals —
    # measured 4x throughput loss through the remote-compile tunnel)
    PREFILL_GROUP = 4

    def _dispatch_mid(self, req: Request) -> int:
        """Dispatch ONE fixed-size no-sample prefill chunk (width 1).
        The chunk prefills at global offset n_cached + prefill_sent
        with everything before it — spliced prefix AND previously
        dispatched chunks — riding along as the prefix page table;
        offsets need not be page-aligned (the attention masks the
        partial last page). A preemption resume's TAIL chunk may be
        shorter than the chunk width: ids are right-padded with zeros
        and the pad K/V aimed at the scratch page (the causal mask
        hides pad keys from real queries, so padding is inert).
        Returns the number of real tokens dispatched (0 when the
        dispatch failed and the request was unwound)."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        c = self.prefill_chunk or self._recompute_chunk
        toks = req.prefill_tokens
        off = req.n_cached + req.prefill_sent
        take = min(c, req.suffix_len - req.prefill_sent)
        ids = np.zeros((1, c), np.int32)
        ids[0, :take] = toks[off:off + take]
        slots = np.full((1, c), self._scratch_slot, np.int32)
        try:
            for j in range(take):
                slots[0, j] = self._extend_with_preempt(req)
        except KVCacheExhausted as e:
            self.time_prefill_s += time.perf_counter() - t0
            self._fail_request(req, f"KV pool exhausted mid-prefill "
                                    f"with no preemption victim: {e}")
            return 0
        try:
            if off:
                need = -(-off // cache.block_size)
                width = next(b for b in self._prefix_page_buckets
                             if b >= need)
                ptab = np.full((1, width), self._scratch_block,
                               np.int32)
                pb = cache.seq_blocks(req.req_id)[:need]
                ptab[0, :len(pb)] = pb
                cache.k, cache.v = self._device_call(
                    "dispatch:prefill_mid", self._prefill_mid_j,
                    self.dec.weights, cache.k, cache.v,
                    jnp.asarray(ids), jnp.asarray(slots),
                    jnp.asarray([off], np.int32), jnp.asarray(ptab))
            else:
                cache.k, cache.v = self._device_call(
                    "dispatch:prefill_mid", self._prefill_mid0_j,
                    self.dec.weights, cache.k, cache.v,
                    jnp.asarray(ids), jnp.asarray(slots))
        except _DispatchFailed as e:
            self.time_prefill_s += time.perf_counter() - t0
            self._fail_request(req, f"prefill dispatch failed after "
                                    f"retries: {e}")
            return 0
        req.prefill_sent += take
        if self.tracer is not None:
            self.tracer.event(
                "dispatch", trace=req.trace_id, pid=self.replica_id,
                kind="prefill_mid", rows=1, tokens=int(take),
                offset=int(off))
        self._inflight.append({"kind": "prefill", "toks": None,
                               "group": [], "free_after": []})
        if req.resume and req.prefill_sent >= req.suffix_len:
            self._resume_complete(req)
        self.time_prefill_s += time.perf_counter() - t0
        return take

    def _resume_complete(self, req: Request):
        """A preemption resume finishes at DISPATCH time — no sampling
        final, no collection barrier: the next decode input is the
        already-emitted out_tokens[-1], supplied from the host exactly
        like a fresh prefill's first token."""
        req.state = "running"
        if self.tracer is not None:
            self._trace_running(req, time.perf_counter())
        self._clear_pending_writes(req)
        si = req.slot
        self._last_tok[si] = req.out_tokens[-1]
        self._fresh_slots.add(si)
        req.planned = len(req.out_tokens)

    def _dispatch_final(self, bucket: int, group, gp: int):
        """Dispatch one FINAL (first-token-sampling) prefill for rows
        whose remaining suffix fits a single bucketed dispatch —
        either a whole short prompt or the tail of a chunked one.
        `group` rows are (slot, req, off): `off` counts spliced prefix
        plus already-dispatched chunk tokens, so `bucket` is the
        REMAINDER bucket, RoPE positions/slot mappings start at `off`,
        and the covered pages ride along as a scratch-padded prefix
        table. The dispatch is queued; tokens are fetched at
        collection time."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        vocab = self.dec.cfg.vocab_size
        ids = np.zeros((gp, bucket), np.int32)
        slots = np.full((gp, bucket), self._scratch_slot, np.int32)
        last_idx = np.zeros(gp, np.int32)
        ncv = np.zeros(gp, np.int32)
        ptab = np.full((gp, self._prefix_pages), self._scratch_block,
                       np.int32)
        temps = np.zeros(gp, np.float32)
        top_ks = np.zeros(gp, np.int32)
        top_ps = np.ones(gp, np.float32)
        reps = np.ones(gp, np.float32)
        any_rep = any(req.sampling.repetition_penalty != 1.0
                      for _, req, _ in group)
        seen = np.zeros((gp, vocab), bool) if any_rep else None
        members = [req for _, req, _ in group]
        try:
            for row, (si, req, off) in enumerate(group):
                s = int(req.prompt.size) - off
                ids[row, :s] = req.prompt[off:]
                slots[row, :s] = [
                    self._extend_with_preempt(req, exclude=members)
                    for _ in range(s)]
                last_idx[row] = s - 1
                ncv[row] = off
                if off:
                    pb = cache.seq_blocks(req.req_id)[
                        : -(-off // cache.block_size)]
                    ptab[row, :len(pb)] = pb
                sp = req.sampling
                temps[row] = sp.temperature
                # engine-level top_k is the default where the request
                # does not set its own (None); an explicit 0 disables it
                top_ks[row] = self.top_k if sp.top_k is None \
                    else sp.top_k
                top_ps[row] = sp.top_p
                reps[row] = sp.repetition_penalty
                if sp.repetition_penalty != 1.0:
                    seen[row, req.prompt] = True  # FULL prompt, cached
        except KVCacheExhausted as e:
            # no victim left for the group's suffix slots (only
            # reachable through an injected-fault storm on a
            # worst-case-admitted pool): the group shares one dispatch
            # and its rows are already entangled — fail it whole
            self.time_prefill_s += time.perf_counter() - t0
            for req in members:
                self._fail_request(
                    req, f"KV pool exhausted building prefill "
                         f"group: {e}")
            return
        seen_dev = jnp.asarray(seen) if any_rep \
            else self._zeros_seen(gp, vocab)
        allowed_dev = self._allowed_operand(
            gp, [(row, req.allowed_mask)
                 for row, (_si, req, _off) in enumerate(group)])
        # the suffix-prefix program pays a per-layer page gather plus
        # dense attention over the (possibly all-masked) prefix columns:
        # only groups with at least one covered prefix take it —
        # cold-start groups keep the plain flash prefill, so disjoint
        # unchunked traffic is unchanged
        try:
            if any(off for _, _, off in group):
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:prefill", self._prefill_prefix_j,
                    self.dec.weights, cache.k, cache.v,
                    jnp.asarray(ids), jnp.asarray(slots),
                    jnp.asarray(last_idx), jnp.asarray(ncv),
                    jnp.asarray(ptab), jnp.asarray(temps),
                    self._next_key(), jnp.asarray(top_ks),
                    jnp.asarray(top_ps), jnp.asarray(reps), seen_dev,
                    allowed_dev)
            else:
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:prefill", self._prefill_j,
                    self.dec.weights, cache.k, cache.v,
                    jnp.asarray(ids), jnp.asarray(slots),
                    jnp.asarray(last_idx), jnp.asarray(temps),
                    self._next_key(), jnp.asarray(top_ks),
                    jnp.asarray(top_ps), jnp.asarray(reps), seen_dev,
                    allowed_dev)
        except _DispatchFailed as e:
            # request mutations happen only after a SUCCESSFUL
            # dispatch, so coverage bookkeeping is still truthful here:
            # unwinding restarts exactly the readers whose spliced
            # blocks will now never be written
            self.time_prefill_s += time.perf_counter() - t0
            for req in members:
                self._fail_request(
                    req, f"prefill dispatch failed after retries: {e}")
            return
        for si, req, off in group:
            req.prefill_sent = req.suffix_len
            self._clear_pending_writes(req)
        if self.tracer is not None:
            self.tracer.event("dispatch", pid=self.replica_id,
                              kind="prefill", rows=int(gp),
                              bucket=int(bucket))
        self._inflight.append({"kind": "prefill", "toks": toks,
                               "group": [(si, req, req.epoch)
                                         for si, req, _ in group],
                               "free_after": []})
        self.time_prefill_s += time.perf_counter() - t0

    def _prefill_complete(self, toks: np.ndarray, group):
        """Post-fetch bookkeeping for one collected FINAL prefill:
        the request leaves "prefilling" with its first token. Requests
        that lost their slot while the chunk was in flight (cancel /
        deadline abort / preemption restart — epoch bumped) are
        skipped: their result belongs to a previous life."""
        now = time.perf_counter()
        for row, (si, req, epoch) in enumerate(group):
            if req.state != "prefilling" or req.epoch != epoch:
                continue
            tok = int(toks[row])
            req.state = "running"
            if self.tracer is not None:
                self._trace_running(req, now)
            self._mark_first_token(req, now)
            req.out_tokens.append(tok)
            req.planned = 1
            self.generated_tokens += 1
            self._last_tok[si] = tok
            self._fresh_slots.add(si)
            if self._is_finished(req):
                self._retire(si)

    def _is_finished(self, req: Request) -> bool:
        sp = req.sampling
        return (len(req.out_tokens) >= sp.max_new_tokens
                or (sp.eos_token_id is not None
                    and req.out_tokens[-1] == sp.eos_token_id))

    def _retire(self, si: int):
        req = self._slots[si]
        req.state = "done"
        req.t_done = time.perf_counter()
        # finished-request ITL samples fold into the bounded reservoir
        # here (aborted/failed lifetimes never reach _retire, so the
        # successful-traffic-only percentile contract is preserved)
        self._itl_res.extend(req.itls)
        if self.tracer is not None:
            if req.trace_id is not None:
                self._trace_life_end(req, "done", req.t_done)
                self.tracer.end_request(
                    req.trace_id, "done", replica=self.replica_id,
                    tokens=len(req.out_tokens))
            m = self.tracer.metrics
            if req.latency_s is not None:
                m.histogram("engine.latency_s").observe(req.latency_s)
            if req.ttft_s is not None:
                m.histogram("engine.ttft_s").observe(req.ttft_s)
        self._done[req.req_id] = req
        self._slots[si] = None
        self._lora_release(req)
        if self._inflight:
            # an in-flight chunk still reads/writes this request's pages
            # (it was dispatched assuming continuation): free them only
            # after the LAST dispatched chunk is fetched
            self._inflight[-1]["free_after"].append(req.req_id)
        else:
            self.dec.cache.free(req.req_id)

    def _zeros_seen(self, rows: int, vocab: int):
        """Cached device-resident all-False seen mask (per row count)."""
        cached = self._zeros_seen_cache.get(rows)
        if cached is None:
            cached = self._replicated(jnp.zeros((rows, vocab), bool))
            self._zeros_seen_cache[rows] = cached
        return cached

    def _ones_allowed(self, rows: int, vocab: int):
        """Cached device-resident all-True allowed mask: the identity
        operand every rich dispatch without structured-decoding
        requests ships (no [rows, vocab] host->device traffic)."""
        cached = self._ones_allowed_cache.get(rows)
        if cached is None:
            cached = self._replicated(jnp.ones((rows, vocab), bool))
            self._ones_allowed_cache[rows] = cached
        return cached

    def _allowed_operand(self, rows: int, entries):
        """The allowed-vocab operand for one rich dispatch: ``entries``
        is [(row, mask)] for the requests that restrict their vocab —
        empty reuses the cached all-True identity, and a repeated
        (rows, row->mask) layout reuses the memoized device operand
        (masks are per-request immutable, so a long-running masked
        stream uploads its [rows, vocab] operand once per layout, not
        once per dispatch)."""
        vocab = self.dec.cfg.vocab_size
        entries = [(r, m) for r, m in entries if m is not None]
        if not entries:
            return self._ones_allowed(rows, vocab)
        key = (rows, tuple(sorted((r, id(m)) for r, m in entries)))
        cached = self._allowed_memo.get(key)
        if cached is None:
            if len(self._allowed_memo) >= 256:
                # churn guard: an engine that never clear_finished()es
                # must not accumulate one [rows, vocab] device array
                # per dead layout forever
                self._allowed_memo.clear()
            allowed = np.ones((rows, vocab), bool)
            for r, m in entries:
                allowed[r] = m
            cached = self._replicated(jnp.asarray(allowed)) \
                if self.tp > 1 else jnp.asarray(allowed)
            self._allowed_memo[key] = cached
        return cached

    @staticmethod
    def _normalize_allowed(allowed_tokens, vocab: int) -> np.ndarray:
        """allowed_tokens (bool mask of length vocab, or a sequence of
        allowed token ids) -> [vocab] bool mask; rejects empty masks
        and out-of-range ids at add_request time."""
        arr = np.asarray(allowed_tokens)
        if arr.dtype == bool:
            if arr.shape != (vocab,):
                raise ValueError(
                    f"allowed_tokens bool mask must have shape "
                    f"({vocab},), got {arr.shape}")
            mask = arr.copy()
        else:
            if (arr.ndim == 1 and arr.size == vocab and vocab > 2
                    and np.isin(arr, (0, 1)).all()):
                # an INTEGER 0/1 vector of exactly vocab length is
                # almost certainly a mask built with the wrong dtype —
                # interpreting it as token IDS would silently constrain
                # decoding to tokens {0, 1}
                raise ValueError(
                    f"allowed_tokens is a length-{vocab} integer 0/1 "
                    f"vector — ambiguous between a mask and an id "
                    f"list; pass a bool mask (astype(bool)) or a list "
                    f"of allowed token ids")
            ids = arr.astype(np.int64).reshape(-1)
            if ids.size and (ids.min() < 0 or ids.max() >= vocab):
                raise ValueError(
                    f"allowed_tokens ids out of range [0, {vocab})")
            mask = np.zeros(vocab, bool)
            mask[ids] = True
        if not mask.any():
            raise ValueError("allowed_tokens permits no token — "
                             "nothing could ever be sampled")
        return mask

    # -- multi-tenant adapter bookkeeping (ISSUE 10) -------------------------
    def _lora_acquire(self, req: Request):
        """Fault/pin the request's adapter at admission. Raises
        KVCacheExhausted when its pages cannot be faulted in — the
        caller treats it exactly like a KV allocation refusal."""
        if req.sampling.adapter_id is None or req.lora_held:
            return
        self.lora.acquire(req.sampling.adapter_id)
        req.lora_held = True

    def _lora_release(self, req: Request):
        """Drop the request's pin whenever it loses its slot (retire,
        abort/fail, preemption/restart). At zero users the adapter's
        pages park in the pool LRU — still resident, evictable."""
        if req.lora_held:
            self.lora.release(req.sampling.adapter_id)
            req.lora_held = False

    def _lora_tables_operand(self, sched) -> np.ndarray:
        """[max_b + 1, n_pages] page table for this dispatch's lora
        gather: engine slot -> its request's resident adapter pages
        (scratch block — the all-zero null-adapter page — for
        base-model slots and the scratch row)."""
        width = self.lora.n_pages()
        tables = np.full((self.max_b + 1, width), self._scratch_block,
                         np.int32)
        for rid, (req, _epoch) in sched.items():
            aid = req.sampling.adapter_id
            if aid is not None and req.slot is not None:
                tables[req.slot] = self.lora.resident_blocks(aid)
        return tables

    def _debug_lora_check(self):
        """Cross-check registry use counts against the scheduler's
        slot truth, then the registry's own page invariants (the
        ISSUE-10 half of the per-step debug sweep)."""
        expected: Dict[object, int] = {}
        for r in self._slots:
            if r is not None and r.lora_held:
                aid = r.sampling.adapter_id
                expected[aid] = expected.get(aid, 0) + 1
        self.lora.debug_check(expected_use=expected)

    def _replicated(self, arr):
        """Commit a cached device constant consistently with the
        engine's mesh: replicated over the tp mesh under tensor
        parallelism (a default-device-committed constant would clash
        with the tp-mesh program), as-is otherwise. The spec is
        spelled DIMENSION-WISE (P(None, ..., None), not P()) to match
        the sharding the tp programs' own outputs carry: jit caches on
        the spelling, so a carried operand that alternates between a
        P() constant (first dispatch after idle) and a program output
        (every later dispatch) would trace+compile each (T, W) shape
        TWICE — a silent 2x compile tax CompileWatch caught on the
        sealed tp chaos leg (ISSUE 14)."""
        if self.tp == 1:
            return arr
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(
            arr, NamedSharding(self.dec.mesh, P(*(None,) * arr.ndim)))

    def _warmup_prompt(self, n: int) -> np.ndarray:
        """Throwaway warmup prompt with a per-call token fill: two
        warmup prompts must never share a block-aligned prefix, or the
        prefix cache would splice them together and the full-length
        (bucket, width) prefill programs warmup exists to compile would
        never run."""
        self._warmup_fill = getattr(self, "_warmup_fill", 0) + 1
        v = 1 + self._warmup_fill % max(1, self.dec.cfg.vocab_size - 1)
        return np.full(n, v, np.int32)

    def _rep_active(self) -> bool:
        return any(r is not None and r.state == "running"
                   and r.sampling.repetition_penalty != 1.0
                   for r in self._slots)

    def _pick_chunk(self, active) -> int:
        """Pick the ladder rung for this chunk.

        With a measured per-rung cost table (built by warmup): maximize
        delivered tokens per second — tokens(c) = sum over active slots
        of min(c, remaining budget); cost(c) was measured on THIS
        device/link. Overshooting a slot's budget (it idles on the
        scratch page for the tail) is chosen exactly when the per-chunk
        overhead (e.g. host↔device round trip) outweighs the wasted
        steps — a property of the deployment, not a constant.

        Without the table (warmup not run): zero-waste heuristic —
        largest rung every budget covers when idle; when requests are
        queued, largest rung the SOONEST-draining slot covers (so its
        slot frees promptly). Either way, queue pressure with EOS-able
        requests pins the smallest rung: such a slot may free any step.
        """
        if len(self.chunks) == 1:
            return self.chunks[0]
        if self._queue and any(
                self._slots[si].sampling.eos_token_id is not None
                for si in active):
            return self.chunks[0]
        lefts = [self._slots[si].sampling.max_new_tokens
                 - self._slots[si].planned for si in active]
        if self._chunk_cost:
            best, best_rate = self.chunks[0], -1.0
            for c in self.chunks:
                cost = self._chunk_cost.get(c)
                if cost is None:
                    continue
                tokens = sum(min(c, max(0, lf)) for lf in lefts)
                rate = tokens / cost
                if rate > best_rate + 1e-9:
                    best, best_rate = c, rate
            return best
        bound = min(lefts) if self._queue else max(lefts)
        best = self.chunks[0]
        for c in self.chunks[1:]:
            if c <= bound:
                best = c
        return best

    def _newest_decode_entry(self):
        for e in reversed(self._inflight):
            if e["kind"] == "decode":
                return e
        return None

    def _dispatch_chunk(self) -> bool:
        """Dispatch ONE decode chunk for the current RUNNING slots
        without waiting for the previous chunk: first tokens of
        continuing slots are gathered from the in-flight chunk's DEVICE
        output (no host round trip); freshly admitted slots take their
        prefill token from the host. Slots still mid-prefill aim at the
        scratch page like inactive ones."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        active = [si for si in range(self.max_b)
                  if self._slots[si] is not None
                  and self._slots[si].state == "running"]
        if not active:
            self.time_host_s += time.perf_counter() - t0
            return False
        T = self._force_chunk or self._pick_chunk(active)
        mb, mp = self.max_b, self.dec.max_pages
        # host-precomputed page schedule: slots past their token budget
        # (or inactive) aim at the scratch page for the rest of the chunk
        tables = np.full((T, mb, mp), self._scratch_block, np.int32)
        ctx = np.zeros((T, mb), np.int32)
        slots = np.full((T, mb), self._scratch_slot, np.int32)
        temps = np.zeros(mb, np.float32)
        top_ks = np.zeros(mb, np.int32)
        top_ps = np.ones(mb, np.float32)
        reps = np.ones(mb, np.float32)
        vocab = self.dec.cfg.vocab_size
        steps_of: Dict[int, int] = {}
        reqs_of: Dict[int, Request] = {}
        epochs_of: Dict[int, int] = {}
        def neutralize(vsi: int):
            """Blank a slot's rows in THIS chunk's schedule. A victim
            preempted mid-build frees blocks a LATER slot of the same
            chunk may take — but its already-scheduled rows would then
            write K/V into the same flat slots within ONE program,
            silently corrupting the surviving request (device program
            order only protects cross-program reuse). Re-aiming the
            victim's rows at the scratch page removes the overlap.
            The victim's sampling contribution is dropped too: a
            processed row would otherwise keep the whole chunk on the
            rich program (unwarmed XLA variant + [mb, vocab] seen
            matrix) even when every surviving row is greedy."""
            slots[:, vsi] = self._scratch_slot
            ctx[:, vsi] = 0
            tables[:, vsi, :] = self._scratch_block
            steps_of.pop(vsi, None)
            reqs_of.pop(vsi, None)
            epochs_of.pop(vsi, None)
            temps[vsi] = 0.0
            top_ks[vsi] = 0
            top_ps[vsi] = 1.0
            reps[vsi] = 1.0

        for si in active:
            req = self._slots[si]
            if req is None or req.state != "running":
                # preempted by an earlier slot's KV pressure while this
                # chunk was being scheduled
                continue
            sp = req.sampling
            # budget at DISPATCH time: tokens planned (dispatched), not
            # tokens fetched — EOS cuts are discovered at collection
            steps = max(0, min(T, sp.max_new_tokens - req.planned))
            try:
                for t in range(steps):
                    ctx[t, si] = cache.context_len(req.req_id)
                    while True:
                        try:
                            slots[t, si] = cache.extend(req.req_id)
                            break
                        except KVCacheExhausted:
                            victim = self._pick_victim()
                            if victim is None or victim is req:
                                raise
                            vsi = victim.slot
                            self._preempt(victim)
                            if vsi is not None:
                                neutralize(vsi)
            except KVCacheExhausted:
                # req itself is the policy victim (newest / lowest
                # priority): preempt it and blank its partial rows —
                # its freed pages may be re-taken by a later slot of
                # this very chunk. A recompute-incapable decoder has
                # no resume programs (_pick_victim always returns None
                # for it), so preempting would re-admit into a mid
                # path that doesn't exist — fail the request instead.
                if self._can_recompute:
                    self._preempt(req)
                else:
                    self._fail_request(
                        req, "KV pool exhausted and decoder does not "
                             "support preemption-with-recompute")
                neutralize(si)
                continue
            req.planned += steps
            steps_of[si] = steps
            reqs_of[si] = req
            epochs_of[si] = req.epoch
            temps[si] = sp.temperature
            top_ks[si] = self.top_k if sp.top_k is None else sp.top_k
            top_ps[si] = sp.top_p
            reps[si] = sp.repetition_penalty
            # one table per slot per chunk: after the extends above the
            # block list is final for the whole chunk, and entries past
            # a step's context length are masked by ctx anyway
            tables[:, si, :] = cache.block_table(req.req_id, mp)[None]
        # computed over SURVIVORS only — neutralize() may have dropped
        # an already-accumulated victim row
        rich = any(r.sampling.needs_rich_sampling
                   for r in reqs_of.values())
        if all(s == 0 for s in steps_of.values()):
            # every active slot is budget-drained and just awaiting
            # collection — nothing to run
            self.time_host_s += time.perf_counter() - t0
            return False

        # first tokens: device gather from the newest in-flight DECODE
        # chunk for continuing slots, host values for fresh/0-step
        # slots (prefill entries between them don't carry decode toks)
        try:
            prev = self._newest_decode_entry()
            if prev is not None:
                last_idx = np.zeros(mb, np.int32)
                override = np.asarray(self._last_tok, np.int32).copy()
                use_host = np.ones(mb, bool)
                for si, req in reqs_of.items():
                    psteps = prev["steps"].get(si, 0)
                    if (psteps > 0 and si not in self._fresh_slots
                            and prev["reqs"].get(si) is req
                            and prev["epochs"].get(si) == req.epoch):
                        use_host[si] = False
                        last_idx[si] = psteps - 1
                first_ids = self._device_call(
                    "dispatch:merge", self._merge_first_j,
                    prev["toks"], jnp.asarray(last_idx),
                    jnp.asarray(override), jnp.asarray(use_host))
            else:
                first_ids = jnp.asarray(self._last_tok)
            self._fresh_slots.clear()

            keys = jax.random.split(self._next_key(), T)
            if rich:
                if any(r.sampling.repetition_penalty != 1.0
                       for r in reqs_of.values()):
                    seen = np.zeros((mb, vocab), bool)
                    for si, req in reqs_of.items():
                        if req.sampling.repetition_penalty != 1.0:
                            seen[si, req.prompt] = True
                            if req.out_tokens:
                                seen[si,
                                     np.asarray(req.out_tokens)] = True
                    seen_dev = jnp.asarray(seen)
                else:
                    # top_k/top_p-only chunk: the mask is multiplied by
                    # (rep != 1) == False in-program — reuse a cached
                    # device-resident zeros mask instead of shipping
                    # [mb, vocab] bools through the tunnel every chunk
                    seen_dev = self._zeros_seen(mb, vocab)
                allowed_dev = self._allowed_operand(
                    mb, [(si, r.allowed_mask)
                         for si, r in reqs_of.items()])
                self.masked_decode_columns += sum(
                    1 for si, r in reqs_of.items()
                    if r.allowed_mask is not None
                    and steps_of.get(si, 0) > 0)
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:decode", self._decode_rich_j,
                    self.dec.weights, cache.k, cache.v, first_ids,
                    jnp.asarray(tables), jnp.asarray(ctx),
                    jnp.asarray(slots), jnp.asarray(temps), keys,
                    jnp.asarray(top_ks), jnp.asarray(top_ps),
                    jnp.asarray(reps), seen_dev, allowed_dev)
            else:
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:decode", self._decode_j,
                    self.dec.weights, cache.k, cache.v, first_ids,
                    jnp.asarray(tables), jnp.asarray(ctx),
                    jnp.asarray(slots), jnp.asarray(temps), keys)
        except _DispatchFailed as e:
            # transient device error that survived the retry budget:
            # the chunk's requests fail with a structured error — the
            # ENGINE keeps serving (0-step slots awaiting collection
            # and still-prefilling requests are untouched)
            for si, steps in steps_of.items():
                req = reqs_of[si]
                if steps > 0 and self._slots[si] is req \
                        and req.state == "running":
                    self._fail_request(
                        req, f"decode dispatch failed after retries: "
                             f"{e}")
            self.time_host_s += time.perf_counter() - t0
            return False
        if self.tracer is not None:
            self.tracer.event(
                "dispatch", pid=self.replica_id, kind="decode",
                T=int(T), width=self.max_b,
                rows=sum(1 for s in steps_of.values() if s > 0),
                tokens=int(sum(steps_of.values())))
        self._inflight.append({"kind": "decode", "toks": toks,
                               "steps": steps_of, "reqs": reqs_of,
                               "epochs": epochs_of,
                               "T": T, "free_after": []})
        self.time_host_s += time.perf_counter() - t0
        return True

    # -- ragged unified scheduler (ISSUE 5) ----------------------------------
    # row-count buckets for the ragged [T, W] schedule: W pads up to the
    # next rung (the ONLY padding left on this path — stats() counts it
    # as padded_token_waste), so compile variants stay ~log-bounded
    RAGGED_WIDTHS = (1, 2, 4, 8, 12, 16, 24, 32, 48, 64, 96, 128)
    # prefill rows per pure-prefill (idle) ragged program: no decode
    # stream is waiting, so bursts drain in few wide programs instead
    # of being serialized across steps by the interleaving budget
    _RAGGED_IDLE_CAP = 256

    def _ragged_width(self, w: int) -> int:
        for b in self.RAGGED_WIDTHS:
            if w <= b:
                return b
        return -(-w // 64) * 64

    def _newest_ragged_entry(self):
        for e in reversed(self._inflight):
            if e["kind"] == "ragged":
                return e
        return None

    def _zeros_toks(self, t: int, w: int):
        """Cached device-resident zero [T, W] token block: the
        prev-toks operand of the FIRST ragged dispatch after a pipeline
        flush (every column takes its host override)."""
        cached = self._zeros_toks_cache.get((t, w))
        if cached is None:
            cached = self._replicated(jnp.zeros((t, w), jnp.int32))
            self._zeros_toks_cache[(t, w)] = cached
        return cached

    def _ragged_plan(self):
        """(T, dcols, takes, fused): this step's decode columns and
        prefill token takes, computed WITHOUT touching the allocator —
        the shape pre-pass that fixes the (T, W) program variant before
        any page is claimed (so a variant mismatch with the in-flight
        chunk can flush the pipeline BEFORE the schedule is built).
        ``fused`` marks a multi-step window (ISSUE 16): in the
        pure-decode regime — running slots, NO prefilling slot — the
        plan scales the chunk rung to k*T ministeps, fusing k serving
        steps into one program; any prefilling slot (mid-prefill,
        splice-pending, fresh admission) drops back to single-step
        chunks so chunked-prefill ITL bounds and splice watermarks
        keep their per-step granularity."""
        running = [si for si in range(self.max_b)
                   if self._slots[si] is not None
                   and self._slots[si].state == "running"]
        T = self._force_chunk or (self._pick_chunk(running) if running
                                  else 1)
        fused = (self.multi_step > 1 and bool(running)
                 and not any(r is not None and r.state == "prefilling"
                             for r in self._slots))
        if fused:
            T = T * self.multi_step
        dcols = []
        for si in running:
            req = self._slots[si]
            steps = max(0, min(T, req.sampling.max_new_tokens
                               - req.planned))
            if steps > 0:
                dcols.append((si, req, steps))
        takes = []
        # while decodes run, the budget bounds how much prefill slots
        # between consecutive decode ministep groups (the running
        # streams' worst-case added ITL — dense-path semantics); an
        # idle engine widens to drain bursts in few programs, and
        # _dispatch_ragged keeps issuing pure-prefill chunks until the
        # backlog is gone
        budget = self._ragged_cap if dcols \
            else max(self._ragged_cap, self._ragged_idle_cap)
        pending = sorted((r for r in self._slots
                          if r is not None and r.state == "prefilling"
                          and r.prefill_sent < r.suffix_len),
                         key=lambda r: r.req_id)
        for r in pending:
            if budget <= 0:
                break
            if not self._deps_ready(r):
                # splice-pending reader: its writer's covering chunk has
                # not been DISPATCHED yet (same watermark rule as the
                # dense path — a reader never rides the same or an
                # earlier program than its writer's covering rows)
                continue
            take = min(budget, r.suffix_len - r.prefill_sent)
            takes.append((r, take))
            budget -= take
        return T, dcols, takes, fused

    def _dispatch_ragged(self) -> bool:
        """Dispatch this step's ragged work: the speculative verify
        chunk when drafting applies (ISSUE 9 — greedy decode columns
        with draft hits), else ONE unified chunk in the steady mixed
        regime; a pure-prefill backlog (no running decodes — cold
        start, burst admission) keeps issuing bounded prefill-only
        chunks until nothing is ready, mirroring the dense idle path's
        unbudgeted _dispatch_prefill (each program is dispatched
        before the next is built, so a splice reader's same-step
        chunks still follow its writer's in device order)."""
        if self._dispatch_spec_chunk():
            return True
        if not self._dispatch_ragged_chunk():
            return False
        while (not any(r is not None and r.state == "running"
                       for r in self._slots)
               and self._dispatch_ragged_chunk()):
            pass
        return True

    # -- speculative decoding (ISSUE 9) --------------------------------------
    def _spec_probe(self) -> bool:
        """Would ANY running greedy column draft right now, judged on
        the possibly-stale (in-flight-chunk-lagged) history? Pure host
        work — used to decide whether a pipeline flush is worth
        paying; windows are never built from this, only from flushed
        truth in _dispatch_spec_chunk."""
        for r in self._slots:
            if (r is None or r.state != "running"
                    or r.sampling.temperature > 0.0
                    or r.sampling.needs_rich_sampling):
                continue
            left = r.sampling.max_new_tokens - r.planned
            if left <= 1:
                continue
            hist = np.concatenate(
                [r.prompt, np.asarray(r.out_tokens, np.int32)])
            if np.asarray(self._drafter.propose(
                    hist, min(self.spec.draft_len, left - 1))).size:
                return True
        return False

    def _dispatch_spec_chunk(self) -> bool:
        """Dispatch ONE speculative verify+decode chunk: every greedy
        running column rides as 1 + k ragged rows (its carried token
        plus the drafter's k proposals at consecutive positions), the
        teacher verifies all positions in a single forward, and
        acceptance/neutralization happen in-program (_spec_accept).
        Prefill-chunk rows ride along under what is left of the
        per-step row budget after the draft fan-out. Returns False —
        the caller falls back to the plain ragged chunk — when spec is
        off, any slotted request needs rich sampling (its per-column
        seen-mask semantics don't compose with multi-row columns), no
        column is running, or the drafter proposed nothing this step
        (a 1-ministep chunk with no drafts is strictly worse than the
        T-ministep ragged program).

        The verify chunk is SYNCHRONOUS by construction (step()
        collects it before returning): the accepted count decides the
        next step's positions, slots and drafts, so there is nothing
        correct to pipeline behind it. The flush below also makes the
        drafter's history exact — an in-flight chunk's tokens are
        device-side and drafting against stale history would verify
        the wrong positions."""
        if self.spec is None:
            return False
        if any(r is not None and r.sampling.needs_rich_sampling
               for r in self._slots):
            return False
        if not any(r is not None and r.state == "running"
                   for r in self._slots):
            return False
        # cheap probe on the CURRENT (at most one-chunk-stale) history
        # BEFORE paying the pipeline flush: on a low-hit workload the
        # drafter misses every step, and flushing first would disable
        # the ragged path's overlap permanently. A probe hit flushes
        # and re-proposes against exact history (a window is only ever
        # BUILT from flushed truth); a probe miss that exact history
        # would have hit merely delays spec by one step.
        if self._inflight and not self._spec_probe():
            return False
        while self._inflight:
            self._collect_oldest()
        t0 = time.perf_counter()
        cache = self.dec.cache
        mp = self.dec.max_pages
        dcols: List[Tuple[int, Request, np.ndarray]] = []
        total_drafts = 0
        for si in range(self.max_b):
            req = self._slots[si]
            if req is None or req.state != "running":
                continue
            left = req.sampling.max_new_tokens - req.planned
            if left <= 0:
                continue
            drafts = np.zeros(0, np.int32)
            if req.sampling.temperature <= 0.0 and left > 1:
                # drafts clamp to the window AND the remaining budget
                # (re-clipped after propose: a Drafter that ignores
                # its k contract must not inflate the verify window or
                # starve the prefill row budget): a draft past either
                # bound could never be delivered — pure row waste
                k = min(self.spec.draft_len, left - 1)
                hist = np.concatenate(
                    [req.prompt, np.asarray(req.out_tokens, np.int32)])
                drafts = np.asarray(
                    self._drafter.propose(hist, k),
                    np.int32).reshape(-1)[:k]
            dcols.append((si, req, drafts))
            total_drafts += len(drafts)
        if total_drafts == 0:
            self.time_host_s += time.perf_counter() - t0
            return False
        # draft rows COMPETE with prefill chunks under the per-step
        # row budget: both are extra rows of the same program, and the
        # budget is the bound on the running streams' added ITL
        budget = max(0, self._ragged_cap - total_drafts)
        takes: List[Tuple[Request, int]] = []
        pending = sorted((r for r in self._slots
                          if r is not None and r.state == "prefilling"
                          and r.prefill_sent < r.suffix_len),
                         key=lambda r: r.req_id)
        for r in pending:
            if budget <= 0:
                break
            if not self._deps_ready(r):
                continue
            take = min(budget, r.suffix_len - r.prefill_sent)
            takes.append((r, take))
            budget -= take

        rows = sum(1 + len(d) for _, _, d in dcols) \
            + sum(t for _, t in takes)
        W = self._ragged_width(rows)
        scratch_row = self.max_b
        ids = np.zeros(W, np.int32)
        pos = np.zeros(W, np.int32)
        slots = np.full(W, self._scratch_slot, np.int32)
        rseq = np.full(W, scratch_row, np.int32)
        rctx = np.zeros(W, np.int32)
        use_ov = np.zeros(W, bool)
        override = np.zeros(W, np.int32)
        temps = np.zeros(W, np.float32)
        seg_start = np.arange(W, dtype=np.int32)
        is_draft = np.zeros(W, bool)
        rows_of: Dict[int, List[int]] = {}       # req_id -> rows
        sched: Dict[int, Tuple[Request, int]] = {}
        spec_of: Dict[int, dict] = {}            # slot -> verify window
        finals: List[Tuple[Request, int, int]] = []
        take_of: Dict[int, int] = {}
        col = 0
        for si, req, drafts in dcols:
            if self._slots[si] is not req or req.state != "running":
                continue   # evicted by an earlier column's KV pressure
            base, span = col, 1 + len(drafts)
            col += span    # the run stays reserved even if preempted
            cells = rows_of.setdefault(req.req_id, [])
            # pre-register (like the ragged chunk): when req becomes
            # its own victim mid-extend the staleness sweep must see
            # it to blank its partial rows
            sched[req.req_id] = (req, req.epoch)
            ctx0 = cache.context_len(req.req_id)
            # table length BEFORE the window's extends: rollback may
            # drop only blocks the window itself appended — a
            # worst-case admission reservation must survive intact
            tbl0 = len(cache.seq_blocks(req.req_id))
            done = 0
            try:
                for j in range(span):
                    c = base + j
                    p = ctx0 + j
                    slot = self._extend_with_preempt(req)
                    slots[c] = slot
                    pos[c] = p
                    rctx[c] = p + 1   # sees context + earlier drafts
                    rseq[c] = si
                    cells.append(c)
                    if j == 0:
                        # the carried token always comes from the host
                        # here: the pipeline was flushed above, so the
                        # last emitted token is host-known by def.
                        use_ov[c] = True
                        override[c] = self._last_tok[si]
                    else:
                        ids[c] = int(drafts[j - 1])
                        is_draft[c] = True
                        seg_start[c] = base
                    done += 1
            except KVCacheExhausted:
                # no preemption victim left for the window's tail.
                # With the BASE row scheduled, degrade gracefully:
                # truncate the window to the rows the pool granted (a
                # k=0 window is a plain decode row) — self-preempting
                # here would replay the identical oversized window on
                # resume and livelock under exactly the pressure that
                # made the pool refuse. Only a base row that cannot
                # extend at all preempts (or fails, on a
                # recompute-incapable decoder), like the ragged path.
                if done == 0:
                    if self._can_recompute:
                        self._preempt(req)
                    else:
                        self._fail_request(
                            req, "KV pool exhausted and decoder does "
                                 "not support "
                                 "preemption-with-recompute")
                    continue
            # per-row temperature over the whole window: a draftable
            # column is greedy (temp <= 0) by construction, but a
            # plain-temperature stochastic column rides as a 1-row
            # window and must keep SAMPLING (its stream is not pinned
            # across spec on/off — the key consumption differs — but
            # it must stay a sample, not silently turn greedy)
            temps[base:base + done] = req.sampling.temperature
            # collection needs only the window geometry: acceptance is
            # read off the program's in-program mask (the draft values
            # already live in the dispatched ids schedule)
            spec_of[si] = {"req": req, "epoch": req.epoch,
                           "base": base, "k": done - 1,
                           "ctx0": ctx0, "tbl0": tbl0}
        # prefill rows after the verify windows. Every row is its own
        # column at T=1, so the ragged chunk's one-sampling-final-per-
        # column constraint is satisfied for free; rich finals cannot
        # appear (spec pauses while any slotted request is rich).
        pi = col
        for req, take in takes:
            if req.state != "prefilling" or req.slot is None:
                continue   # evicted by decode-side pressure mid-build
            si = req.slot
            toks_src = req.prefill_tokens
            base_off = req.n_cached + req.prefill_sent
            cells = rows_of.setdefault(req.req_id, [])
            sched[req.req_id] = (req, req.epoch)
            scheduled = 0
            try:
                for j in range(take):
                    if pi >= W:
                        break
                    off = base_off + j
                    c = pi
                    slot = self._extend_with_preempt(req)
                    ids[c] = int(toks_src[off])
                    pos[c] = off
                    rctx[c] = off + 1
                    slots[c] = slot
                    rseq[c] = si
                    cells.append(c)
                    scheduled += 1
                    pi += 1
                    if not req.resume and off + 1 == len(toks_src):
                        temps[c] = req.sampling.temperature
                        finals.append((req, req.epoch, c))
            except KVCacheExhausted as e:
                self._fail_request(
                    req, f"KV pool exhausted mid-prefill with no "
                         f"preemption victim: {e}")
                continue
            if scheduled:
                take_of[req.req_id] = scheduled

        # staleness sweep (the ragged chunk's, at one ministep): blank
        # every row of every request that lost its life mid-build
        def blank(cell_list):
            for c in cell_list:
                ids[c] = 0
                pos[c] = 0
                slots[c] = self._scratch_slot
                rseq[c] = scratch_row
                rctx[c] = 0
                temps[c] = 0.0
                use_ov[c] = False
                override[c] = 0
                is_draft[c] = False
                seg_start[c] = c

        for rid in list(sched):
            req, epoch = sched[rid]
            if (req.epoch == epoch and req.slot is not None
                    and req.state in ("running", "prefilling")):
                continue
            blank(rows_of.get(rid, []))
            for vsi in [s for s, ent in spec_of.items()
                        if ent["req"] is req]:
                del spec_of[vsi]
            take_of.pop(rid, None)
            finals[:] = [f for f in finals if f[0] is not req]
            del sched[rid]
        if not sched:
            self.time_host_s += time.perf_counter() - t0
            return False

        tables = np.full((self.max_b + 1, mp), self._scratch_block,
                         np.int32)
        for rid, (req, epoch) in sched.items():
            tables[req.slot] = cache.block_table(req.req_id, mp)
        self._fresh_slots.clear()

        key = self._replicated(self._next_key())
        aj = self._aj
        use_lora = self.lora is not None and any(
            req.sampling.adapter_id is not None
            for req, _e in sched.values())
        pre = ()
        prog = self._spec_j
        if use_lora:
            pre = (cache.lora_pool, self._shard_ids,
                   aj(self._lora_tables_operand(sched)))
            prog = self._spec_lora_j
            self.lora_dispatches += 1
            self.lora_rows += sum(
                len(rows_of.get(rid, []))
                for rid, (req, _e) in sched.items()
                if req.sampling.adapter_id is not None)
        args = (self.dec.weights, cache.k, cache.v) + pre + (
            aj(override),
            aj(use_ov), aj(ids), aj(pos), aj(slots), aj(rseq),
            aj(rctx), aj(tables), aj(temps), key, aj(seg_start),
            aj(is_draft))
        try:
            toks, acc, cache.k, cache.v = self._device_call(
                "dispatch:spec", prog, *args)
        except _DispatchFailed as e:
            # one program: every surviving request riding it fails
            # together (the ragged chunk's failure contract)
            for rid, (req, epoch) in sched.items():
                if req.epoch == epoch and req.state in ("running",
                                                        "prefilling"):
                    self._fail_request(
                        req, f"spec dispatch failed after retries: "
                             f"{e}")
            self.time_host_s += time.perf_counter() - t0
            return False

        for rid, (req, epoch) in sched.items():
            take = take_of.get(rid, 0)
            if take and req.state == "prefilling":
                req.prefill_sent += take
                if req.prefill_sent >= req.suffix_len:
                    if req.resume:
                        self._resume_complete(req)
                    else:
                        self._clear_pending_writes(req)
        if self.tracer is not None:
            self.tracer.event(
                "dispatch", pid=self.replica_id, kind="spec",
                W=int(W), drafts=int(total_drafts),
                decode_cols=len(spec_of),
                prefill_rows=int(sum(take_of.values())))
        self._inflight.append({
            "kind": "spec", "toks": toks, "acc": acc, "W": W,
            "spec": spec_of, "finals": list(finals),
            "real_rows": sum(take_of.values()),
            "free_after": []})
        self.time_host_s += time.perf_counter() - t0
        return True

    def _dispatch_ragged_chunk(self) -> bool:
        """Dispatch ONE unified ragged chunk — the whole step's device
        work as a single program: T sequential ministeps over a ragged
        [W]-row token batch whose columns are the running slots' decode
        tokens (sampled in-program, carried ministep-to-ministep, first
        tokens merged in-program from the previous chunk's device
        output) and this step's prefill-chunk tokens (no-sample rows at
        their global offsets, spread across the T ministeps; a prompt's
        final token row samples the request's first token). W is sized
        by the actual rows (bucketed), so inactive batch slots cost
        nothing. Preemption mid-build NEUTRALIZES the victim's ROW
        RANGE (every cell it was scheduled into is re-aimed at the
        scratch page — its freed blocks may be re-taken by later rows
        of this very chunk, and intra-program slot overlap would
        corrupt the survivor's KV), the ragged analogue of the dense
        path's neutralize-by-column. Returns True when dispatched."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        mp = self.dec.max_pages
        T, dcols, takes, fused = self._ragged_plan()
        if not dcols and not takes:
            self.time_host_s += time.perf_counter() - t0
            return False
        ptotal = sum(t for _, t in takes)
        W = self._ragged_width(len(dcols)
                               + (-(-ptotal // T) if ptotal else 0))
        prev = self._newest_ragged_entry()
        if prev is not None and prev["T"] == T and W < prev["W"]:
            # sticky width: a shrink (slot retired, prefill drained)
            # pads up to the in-flight chunk's width instead of
            # flushing the pipeline — only growth forces a flush
            W = prev["W"]
        if prev is not None and (prev["T"] != T or prev["W"] != W):
            # program-variant change (slots came or went, prefill phase
            # shifted): flush the pipeline so first tokens come from
            # the host — the in-program merge consumes the previous
            # chunk's [T, W] output and shapes must line up
            while self._inflight:
                self._collect_oldest()
            # collection may retire slots / deliver first tokens:
            # re-plan against the post-flush scheduler state
            T, dcols, takes, fused = self._ragged_plan()
            if not dcols and not takes:
                self.time_host_s += time.perf_counter() - t0
                return False
            ptotal = sum(t for _, t in takes)
            W = self._ragged_width(len(dcols)
                                   + (-(-ptotal // T) if ptotal else 0))
            prev = None

        scratch_row = self.max_b
        vocab = self.dec.cfg.vocab_size
        ids = np.zeros((T, W), np.int32)
        pos = np.zeros((T, W), np.int32)
        slots = np.full((T, W), self._scratch_slot, np.int32)
        rseq = np.full((T, W), scratch_row, np.int32)
        rctx = np.zeros((T, W), np.int32)
        ucar = np.zeros((T, W), bool)
        temps = np.zeros((T, W), np.float32)
        top_ks = np.zeros((T, W), np.int32)
        top_ps = np.ones((T, W), np.float32)
        reps = np.ones((T, W), np.float32)
        upd = np.zeros(W, bool)
        rows_of: Dict[int, List[Tuple[int, int]]] = {}  # req_id -> cells
        sched: Dict[int, Tuple[Request, int]] = {}  # req_id -> (req, epoch)
        col_of: Dict[int, int] = {}                 # decode si -> column
        steps_of: Dict[int, int] = {}
        reqs_of: Dict[int, Request] = {}
        epochs_of: Dict[int, int] = {}
        take_of: Dict[int, int] = {}     # req_id -> prefill rows scheduled
        finals: List[Tuple[Request, int, int, int]] = []

        # decode columns --------------------------------------------------
        col = 0
        for si, req, steps in dcols:
            if self._slots[si] is not req or req.state != "running":
                # preempted by an earlier column's KV pressure while
                # this chunk was being built
                continue
            sp = req.sampling
            cells = rows_of.setdefault(req.req_id, [])
            # register BEFORE the allocator loop (like the prefill loop
            # below): when req becomes its own preemption victim
            # mid-extend, the staleness sweep only blanks rows of
            # requests it can see in `sched` — an unregistered victim's
            # partial rows would keep aiming reshape_and_cache at its
            # freed pages, which a later row of this very chunk may
            # re-take
            sched[req.req_id] = (req, req.epoch)
            try:
                for t in range(steps):
                    ctx = cache.context_len(req.req_id)
                    slot = self._extend_with_preempt(req)
                    pos[t, col] = ctx
                    rctx[t, col] = ctx + 1
                    slots[t, col] = slot
                    rseq[t, col] = si
                    cells.append((t, col))
            except KVCacheExhausted:
                # req itself is the policy victim (already in `sched`,
                # so the staleness sweep below blanks its partial rows
                # — _preempt bumps the epoch, _fail_request leaves the
                # running state)
                if self._can_recompute:
                    self._preempt(req)
                else:
                    self._fail_request(
                        req, "KV pool exhausted and decoder does not "
                             "support preemption-with-recompute")
                col += 1
                continue
            req.planned += steps
            ucar[:, col] = True
            temps[:, col] = sp.temperature
            top_ks[:, col] = self.top_k if sp.top_k is None else sp.top_k
            top_ps[:, col] = sp.top_p
            reps[:, col] = sp.repetition_penalty
            upd[col] = True
            col_of[si] = col
            steps_of[si] = steps
            reqs_of[si] = req
            epochs_of[si] = req.epoch
            col += 1

        # prefill cells: ministep-major past the decode columns, so a
        # request's tokens are sequential across (t, col) order — a row
        # always lands at the same or a later ministep than every
        # same-sequence row before it (pool writes precede attention
        # within a ministep, so intra-chunk causality holds by row_ctx)
        pcells = [(t, c) for t in range(T) for c in range(col, W)]
        pi = 0
        for req, take in takes:
            if req.state != "prefilling" or req.slot is None:
                continue   # evicted by decode-side pressure mid-build
            si = req.slot
            toks_src = req.prefill_tokens
            base_off = req.n_cached + req.prefill_sent
            cells = rows_of.setdefault(req.req_id, [])
            sched[req.req_id] = (req, req.epoch)
            scheduled = 0
            try:
                for j in range(take):
                    if pi >= len(pcells):
                        break
                    off = base_off + j
                    t, c = pcells[pi]
                    is_final = (not req.resume
                                and off + 1 == len(toks_src))
                    if is_final:
                        # at most one sampling final per COLUMN: its
                        # rich seen mask is seeded per column. Keep
                        # advancing — the next cell's column can hold
                        # an earlier final too (finals of short takes
                        # land on adjacent columns)
                        while any(fc == c for _, _, _, fc in finals):
                            pi += 1
                            if pi >= len(pcells):
                                break
                            t, c = pcells[pi]
                        if pi >= len(pcells):
                            break
                    slot = self._extend_with_preempt(req)
                    ids[t, c] = int(toks_src[off])
                    pos[t, c] = off
                    rctx[t, c] = off + 1
                    slots[t, c] = slot
                    rseq[t, c] = si
                    cells.append((t, c))
                    scheduled += 1
                    pi += 1
                    if is_final:
                        sp = req.sampling
                        temps[t, c] = sp.temperature
                        top_ks[t, c] = (self.top_k if sp.top_k is None
                                        else sp.top_k)
                        top_ps[t, c] = sp.top_p
                        reps[t, c] = sp.repetition_penalty
                        finals.append((req, req.epoch, t, c))
            except KVCacheExhausted as e:
                self._fail_request(
                    req, f"KV pool exhausted mid-prefill with no "
                         f"preemption victim: {e}")
                continue
            if scheduled:
                take_of[req.req_id] = scheduled

        # staleness sweep: neutralize the ROW RANGE of every request
        # that lost its life while the chunk was being built (direct
        # preemption victims AND cascaded reader restarts) — runs
        # BEFORE dispatch, so a blanked row never writes into pages a
        # survivor re-took
        def blank(cell_list):
            for t, c in cell_list:
                ids[t, c] = 0
                pos[t, c] = 0
                slots[t, c] = self._scratch_slot
                rseq[t, c] = scratch_row
                rctx[t, c] = 0
                temps[t, c] = 0.0
                top_ks[t, c] = 0
                top_ps[t, c] = 1.0
                reps[t, c] = 1.0

        for rid in list(sched):
            req, epoch = sched[rid]
            if (req.epoch == epoch and req.slot is not None
                    and req.state in ("running", "prefilling")):
                continue
            blank(rows_of.get(rid, []))
            for si in [s for s, r in reqs_of.items() if r is req]:
                c = col_of.pop(si, None)
                if c is not None:
                    upd[c] = False
                steps_of.pop(si, None)
                reqs_of.pop(si, None)
                epochs_of.pop(si, None)
            take_of.pop(rid, None)
            finals[:] = [f for f in finals if f[0] is not req]
            del sched[rid]
        if not sched:
            # everything scheduled was evicted mid-build
            self.time_host_s += time.perf_counter() - t0
            return False

        # one table row per slot (plus the scratch row at max_b): after
        # the extends above every survivor's block list is final for
        # the whole chunk; entries past a row's ctx are masked anyway
        tables = np.full((self.max_b + 1, mp), self._scratch_block,
                         np.int32)
        for rid, (req, epoch) in sched.items():
            tables[req.slot] = cache.block_table(req.req_id, mp)

        # first decode tokens: previous ragged chunk's device output
        # for continuing columns (merged IN-program), host values for
        # fresh slots — prev["cols"] maps slots to the PREVIOUS chunk's
        # column layout, which need not match this one's
        last_t = np.zeros(W, np.int32)
        prev_col = np.zeros(W, np.int32)
        use_host = np.ones(W, bool)
        override = np.zeros(W, np.int32)
        for si, c in col_of.items():
            req = reqs_of[si]
            override[c] = self._last_tok[si]
            if prev is not None:
                pc = prev["cols"].get(si)
                psteps = prev["steps"].get(si, 0)
                if (pc is not None and psteps > 0
                        and si not in self._fresh_slots
                        and prev["reqs"].get(si) is req
                        and prev["epochs"].get(si) == req.epoch):
                    use_host[c] = False
                    prev_col[c] = pc
                    last_t[c] = psteps - 1
        self._fresh_slots.clear()

        rich = any(r.sampling.needs_rich_sampling
                   for r in reqs_of.values()) \
            or any(f[0].sampling.needs_rich_sampling for f in finals)
        # multi-tenant routing (ISSUE 10): any surviving scheduled
        # request with an adapter routes the whole chunk through the
        # lora program family (base rows read the null page — zero
        # delta); an all-base chunk keeps the UNCHANGED base program,
        # so adapter_id=None traffic is bit-identical to a lora-less
        # engine
        use_lora = self.lora is not None and any(
            req.sampling.adapter_id is not None
            for req, _e in sched.values())
        prev_toks = prev["toks"] if prev is not None \
            else self._zeros_toks(T, W)
        eos = None
        if fused:
            # on-device EOS bookkeeping operand: each surviving decode
            # column's EOS id (-1 = no EOS configured — the column
            # never freezes; the host still cuts at max_new via the
            # steps clamp). Built AFTER the staleness sweep so a
            # blanked column keeps -1 like any other scratch column.
            eos = np.full(W, -1, np.int32)
            for si, c in col_of.items():
                e = reqs_of[si].sampling.eos_token_id
                if e is not None:
                    eos[c] = e
            self.ms_windows += 1
        # under tp the split keys (committed to the default device)
        # re-place replicated on the tp mesh — an async device_put,
        # not a host sync; the key VALUES are identical to the tp=1
        # stream, only the placement changes
        keys = self._replicated(jax.random.split(self._next_key(), T))
        aj = self._aj
        pre = ()
        if use_lora:
            pre = (cache.lora_pool, self._shard_ids,
                   aj(self._lora_tables_operand(sched)))
            self.lora_dispatches += 1
            self.lora_rows += sum(
                len(rows_of.get(rid, []))
                for rid, (req, _e) in sched.items()
                if req.sampling.adapter_id is not None)
        args = (self.dec.weights, cache.k, cache.v) + pre + (
            prev_toks,
            aj(last_t), aj(prev_col), aj(use_host), aj(override),
            aj(ids), aj(pos), aj(slots), aj(rseq), aj(rctx),
            aj(ucar), aj(tables), aj(temps), keys)
        if fused:
            args = args + (aj(eos),)
        try:
            if rich:
                any_rep = any(r.sampling.repetition_penalty != 1.0
                              for r in reqs_of.values()) \
                    or any(f[0].sampling.repetition_penalty != 1.0
                           for f in finals)
                if any_rep:
                    seen = np.zeros((W, vocab), bool)
                    for si, c in col_of.items():
                        req = reqs_of[si]
                        if req.sampling.repetition_penalty != 1.0:
                            seen[c, req.prompt] = True
                            if req.out_tokens:
                                seen[c,
                                     np.asarray(req.out_tokens)] = True
                    for req, _, t, c in finals:
                        if req.sampling.repetition_penalty != 1.0:
                            seen[c, req.prompt] = True
                    seen_dev = aj(seen)
                else:
                    seen_dev = self._zeros_seen(W, vocab)
                # structured decoding: per-COLUMN allowed-vocab masks
                # (decode columns and sampling finals; discarded cells
                # of a shared column are masked harmlessly)
                entries = [(c, reqs_of[si].allowed_mask)
                           for si, c in col_of.items()]
                entries += [(c, req.allowed_mask)
                            for req, _, _t, c in finals]
                allowed_dev = self._allowed_operand(W, entries)
                self.masked_decode_columns += sum(
                    1 for si, _c in col_of.items()
                    if reqs_of[si].allowed_mask is not None)
                if fused:
                    prog = self._ragged_ms_lora_rich_j if use_lora \
                        else self._ragged_ms_rich_j
                else:
                    prog = self._ragged_lora_rich_j if use_lora \
                        else self._ragged_rich_j
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:ragged", prog, *args,
                    aj(top_ks), aj(top_ps), aj(reps), seen_dev,
                    aj(upd), allowed_dev)
            else:
                if fused:
                    prog = self._ragged_ms_lora_j if use_lora \
                        else self._ragged_ms_j
                else:
                    prog = self._ragged_lora_j if use_lora \
                        else self._ragged_j
                toks, cache.k, cache.v = self._device_call(
                    "dispatch:ragged", prog, *args)
        except _DispatchFailed as e:
            # the unified chunk is ONE program: every surviving request
            # riding it fails together, with a structured error — the
            # engine keeps serving (0-step slots awaiting collection
            # and unscheduled prefills are untouched)
            for rid, (req, epoch) in sched.items():
                if req.epoch == epoch and req.state in ("running",
                                                        "prefilling"):
                    self._fail_request(
                        req, f"ragged dispatch failed after retries: "
                             f"{e}")
            self.time_host_s += time.perf_counter() - t0
            return False

        # post-dispatch bookkeeping: the scheduled prefill rows are now
        # DISPATCHED — bump the splice watermark, complete resumes (no
        # sampling final; decode restarts from the host-held last
        # token), clear pending-write registrations of finished finals
        for rid, (req, epoch) in sched.items():
            take = take_of.get(rid, 0)
            if take and req.state == "prefilling":
                req.prefill_sent += take
                if req.prefill_sent >= req.suffix_len:
                    if req.resume:
                        self._resume_complete(req)
                    else:
                        self._clear_pending_writes(req)
        if self.tracer is not None:
            # k + decode_toks feed trace_report's dispatch-
            # amortization table (tokens scheduled per program launch,
            # split by fused-window depth)
            self.tracer.event(
                "dispatch", pid=self.replica_id, kind="ragged",
                T=int(T), W=int(W), decode_cols=len(col_of),
                prefill_rows=int(sum(take_of.values())),
                finals=len(finals),
                k=int(self.multi_step if fused else 1),
                decode_toks=int(sum(steps_of.values())))
        self._inflight.append({
            "kind": "ragged", "toks": toks, "T": T, "W": W,
            "cols": dict(col_of), "steps": dict(steps_of),
            "reqs": dict(reqs_of), "epochs": dict(epochs_of),
            "finals": list(finals),
            "real_rows": sum(take_of.values()),
            "k": self.multi_step if fused else 1,
            "free_after": []})
        self.time_host_s += time.perf_counter() - t0
        return True

    def _collect_ragged(self, ch):
        """Fetch and process one collected ragged chunk: decode columns
        deliver up to `steps` tokens (epoch-guarded, mid-chunk EOS cut),
        sampling-final rows deliver their request's first token
        (completing the prefill), mid-chunk prefill rows carry no
        result. ITL attribution matches the dense path."""
        t0 = time.perf_counter()
        try:
            # THE designed blocking point of the ragged pipeline, in
            # device program order (retried on transient fetch faults)
            toks = np.asarray(self._device_call(  # flightcheck: disable=FC301
                "collect:ragged", np.asarray, ch["toks"]))
        except _DispatchFailed as e:
            self.time_stall_s += time.perf_counter() - t0
            for si, steps in ch["steps"].items():
                req = ch["reqs"][si]
                if steps > 0 and req.state == "running" \
                        and req.epoch == ch["epochs"].get(si) \
                        and self._slots[si] is req:
                    self._fail_request(
                        req, f"chunk collection failed after retries: "
                             f"{e}")
            for req, epoch, _, _ in ch["finals"]:
                if req.state == "prefilling" and req.epoch == epoch:
                    self._fail_request(
                        req, f"prefill collection failed after "
                             f"retries: {e}")
            for rid in ch["free_after"]:
                self.dec.cache.free(rid)
            return
        self.time_stall_s += time.perf_counter() - t0
        now = time.perf_counter()
        self.decode_steps += ch["T"]
        # ragged utilization accounting: the program ran T x W cells
        # (T is the WINDOW length k*T under multi_step — entry "T"
        # carries the per-iteration row count, so tokens_per_dispatch
        # and padded_token_waste stay honest per ministep); useful
        # work = delivered decode tokens + real prefill rows, so
        # padded_token_waste is the true pad-to-grid remainder (plus
        # genuine post-EOS discards) — no scratch-slot steady waste
        self.decode_slot_steps += ch["T"] * ch["W"]
        self.decode_useful_tokens += ch["real_rows"]
        for si, steps in ch["steps"].items():
            req = ch["reqs"][si]
            if req.state != "running" \
                    or req.epoch != ch["epochs"].get(si):
                continue   # retired/preempted while the chunk flew
            c = ch["cols"][si]
            delivered = 0
            for t in range(steps):
                tok = int(toks[t, c])
                req.out_tokens.append(tok)
                delivered += 1
                self.generated_tokens += 1
                self._last_tok[si] = tok
                if self._is_finished(req):
                    break      # mid-chunk EOS: discard the tail
            fin = self._is_finished(req)
            if fin and delivered < steps and ch.get("k", 1) > 1:
                # the in-window EOS froze this column: the remaining
                # scheduled ministeps ran as scratch-aimed no-ops —
                # count them so the fused path's waste is honest
                self.ms_frozen_token_waste += steps - delivered
            self.decode_useful_tokens += delivered
            self._note_itl(req, now, delivered)
            if fin and self._slots[si] is req:
                self._retire(si)
        for req, epoch, t, c in ch["finals"]:
            if req.state != "prefilling" or req.epoch != epoch:
                continue
            si = req.slot
            tok = int(toks[t, c])
            req.state = "running"
            if self.tracer is not None:
                self._trace_running(req, now)
            self._mark_first_token(req, now)
            req.out_tokens.append(tok)
            req.planned = 1
            self.generated_tokens += 1
            self._last_tok[si] = tok
            self._fresh_slots.add(si)
            if self._is_finished(req):
                self._retire(si)
        for rid in ch["free_after"]:
            self.dec.cache.free(rid)

    def _collect_spec(self, ch):
        """Fetch and process one speculative verify chunk: per verify
        window, count the accepted prefix off the in-program mask,
        deliver accepted drafts + the bonus token (EOS / budget cut
        mid-window like any decode chunk), and ROLL the allocator BACK
        past the delivered tokens — the rejected tail's slots return
        so the next extend re-issues and overwrites them. Final
        prefill rows deliver their first token exactly like the ragged
        chunk's."""
        t0 = time.perf_counter()
        cache = self.dec.cache
        try:
            # the spec pipeline's designed blocking point (sync by
            # construction — acceptance decides the next schedule);
            # one batched fetch for tokens + accepted mask
            fetched = self._device_call(  # flightcheck: disable=FC301
                "collect:spec", jax.device_get, [ch["toks"], ch["acc"]])
        except _DispatchFailed as e:
            self.time_stall_s += time.perf_counter() - t0
            for si, ent in ch["spec"].items():
                req = ent["req"]
                if req.state == "running" \
                        and req.epoch == ent["epoch"] \
                        and self._slots[si] is req:
                    self._fail_request(
                        req, f"spec collection failed after retries: "
                             f"{e}")
            for req, epoch, _ in ch["finals"]:
                if req.state == "prefilling" and req.epoch == epoch:
                    self._fail_request(
                        req, f"prefill collection failed after "
                             f"retries: {e}")
            for rid in ch["free_after"]:
                cache.free(rid)
            return
        toks = np.asarray(fetched[0])
        acc = np.asarray(fetched[1])
        self.time_stall_s += time.perf_counter() - t0
        now = time.perf_counter()
        self.decode_steps += 1
        self.decode_slot_steps += ch["W"]
        self.decode_useful_tokens += ch["real_rows"]
        for si, ent in ch["spec"].items():
            req = ent["req"]
            if req.state != "running" or req.epoch != ent["epoch"] \
                    or self._slots[si] is not req:
                continue   # retired/preempted while the chunk flew
            base, k, ctx0 = ent["base"], ent["k"], ent["ctx0"]
            m = 0
            while m < k and acc[base + 1 + m]:
                m += 1
            self.drafted_tokens += k
            self.accepted_draft_tokens += m
            if k:
                # per-window acceptance EMA (alpha 0.1): the adaptive-
                # window signal (ROADMAP 2), sampled into the
                # acceptance_ema counter track each step
                self.draft_acceptance_ema += 0.1 * (
                    m / k - self.draft_acceptance_ema)
            if m < k:
                self.spec_rollbacks += 1
            if self.tracer is not None and k:
                self.tracer.event(
                    "spec_window", trace=req.trace_id,
                    pid=self.replica_id, drafted=int(k),
                    accepted=int(m))
            delivered = 0
            for j in range(m + 1):
                tok = int(toks[base + j])
                req.out_tokens.append(tok)
                delivered += 1
                self.generated_tokens += 1
                self._last_tok[si] = tok
                if self._is_finished(req):
                    break      # EOS cut mid-draft-window
            self.decode_useful_tokens += delivered
            # sync collection: with nothing in flight, dispatched ==
            # delivered is the planned invariant (the window's
            # rejected remainder was never "planned work")
            req.planned = len(req.out_tokens)
            self._note_itl(req, now, delivered)
            if self._drafter is not None and k:
                self._drafter.observe(
                    np.concatenate(
                        [req.prompt,
                         np.asarray(req.out_tokens, np.int32)]),
                    m, k)
            if self._is_finished(req) and self._slots[si] is req:
                self._retire(si)
            else:
                # position/KV rollback: context length snaps to
                # exactly the KV the delivered prefix wrote (the
                # bonus token's KV is NOT written — it is the next
                # step's input like any freshly sampled token).
                # min_blocks: only blocks the window's own extends
                # appended may drop — never the admission reservation
                cache.rollback(req.req_id, ctx0 + delivered,
                               min_blocks=ent["tbl0"])
        for req, epoch, c in ch["finals"]:
            if req.state != "prefilling" or req.epoch != epoch:
                continue
            si = req.slot
            tok = int(toks[c])
            req.state = "running"
            if self.tracer is not None:
                self._trace_running(req, now)
            self._mark_first_token(req, now)
            req.out_tokens.append(tok)
            req.planned = 1
            self.generated_tokens += 1
            self._last_tok[si] = tok
            self._fresh_slots.add(si)
            if self._is_finished(req):
                self._retire(si)
        for rid in ch["free_after"]:
            cache.free(rid)

    def _note_itl(self, req: Request, now: float, delivered: int):
        """Per-token ITL attribution at collection, shared by the
        decode/ragged/spec collect paths: the chunk's wall interval
        split evenly over the tokens it delivered to this request
        (recorded on the request; mirrored into the engine.itl_s
        fixed-bucket histogram when tracing is on)."""
        if not delivered:
            return
        if req.t_last_emit is not None:
            itl = (now - req.t_last_emit) / delivered
            req.itls.extend([itl] * delivered)
            if self.tracer is not None:
                self.tracer.metrics.histogram(
                    "engine.itl_s").observe(itl, n=delivered)
            if self._slo is not None:
                # one weighted append per chunk — the SLO windows see
                # every delivered token without a per-token append
                self._slo.observe("itl", itl, self._slo_attrs(req),
                                  n=delivered, now=now)
        req.t_last_emit = now

    def _collect_oldest(self):
        """Fetch and process the oldest in-flight chunk — prefill or
        decode (the only host-blocking points of the engine). Mid
        prefill chunks carry no result and cost no fetch; final
        prefill chunks deliver the first token; decode chunks deliver
        T tokens per live slot and are timestamped here for the ITL
        accounting (the chunk's wall interval is attributed evenly
        over the tokens it delivered to each request)."""
        ch = self._inflight.popleft()
        if ch["kind"] == "spec":
            self._collect_spec(ch)
            return
        if ch["kind"] == "ragged":
            self._collect_ragged(ch)
            return
        if ch["kind"] == "prefill":
            if ch["toks"] is not None:
                t0 = time.perf_counter()
                try:
                    # THE designed blocking point for a lone prefill
                    # entry (runs of >1 batch through
                    # _collect_prefill_run); retried on transient fetch
                    # faults — a fetch never consumes the device buffer
                    toks = np.asarray(self._device_call(  # flightcheck: disable=FC301
                        "collect:prefill", np.asarray, ch["toks"]))
                except _DispatchFailed as e:
                    self.time_prefill_s += time.perf_counter() - t0
                    self._fail_prefill_group(ch["group"], e)
                    for rid in ch["free_after"]:
                        self.dec.cache.free(rid)
                    return
                self.time_prefill_s += time.perf_counter() - t0
                self._prefill_complete(toks, ch["group"])
            for rid in ch["free_after"]:
                self.dec.cache.free(rid)
            return
        t0 = time.perf_counter()
        try:
            # THE designed blocking point of the decode pipeline:
            # collection fetches the oldest in-flight chunk, in device
            # program order (retried on transient fetch faults; the
            # outer asarray is a no-op re-wrap of the fetched host
            # array)
            toks = np.asarray(self._device_call(  # flightcheck: disable=FC301
                "collect:decode", np.asarray, ch["toks"]))
        except _DispatchFailed as e:
            self.time_stall_s += time.perf_counter() - t0
            for si, steps in ch["steps"].items():
                req = ch["reqs"][si]
                if steps > 0 and req.state == "running" \
                        and req.epoch == ch["epochs"].get(si) \
                        and self._slots[si] is req:
                    self._fail_request(
                        req, f"chunk collection failed after retries: "
                             f"{e}")
            for rid in ch["free_after"]:
                self.dec.cache.free(rid)
            return
        self.time_stall_s += time.perf_counter() - t0
        now = time.perf_counter()
        self.decode_steps += ch["T"]
        self.decode_slot_steps += ch["T"] * self.max_b
        for si, steps in ch["steps"].items():
            req = ch["reqs"][si]
            if req.state != "running" \
                    or req.epoch != ch["epochs"].get(si):
                continue   # retired/preempted while the chunk flew
            delivered = 0
            for t in range(steps):
                tok = int(toks[si, t])
                req.out_tokens.append(tok)
                delivered += 1
                self.generated_tokens += 1
                self._last_tok[si] = tok
                if self._is_finished(req):
                    break      # mid-chunk EOS: discard the tail
            self.decode_useful_tokens += delivered
            self._note_itl(req, now, delivered)
            if self._is_finished(req) and self._slots[si] is req:
                self._retire(si)
        for rid in ch["free_after"]:
            self.dec.cache.free(rid)

    def _collect_prefill_run(self, n: int):
        """Collect `n` CONSECUTIVE leading prefill entries with ONE
        batched device_get: through the remote tunnel a blocking fetch
        costs a full round trip (~75 ms), so a 16-request burst over 4
        final groups must pay it once, not once per group (measured
        r5: capacity-row prefill wall 0.47 s -> ~0.15 s for 17.6 ms of
        device work) — the chunk pipeline's analog of the batched
        fetch the old blocking admission used. No-sample mid entries
        carry no result and are skipped by the fetch."""
        chs = [self._inflight.popleft() for _ in range(n)]
        t0 = time.perf_counter()
        fetch = [ch["toks"] for ch in chs if ch["toks"] is not None]
        try:
            # designed batched fetch: one tunnel round trip per prefill
            # run (retried whole on transient faults — fetches never
            # consume device buffers)
            fetched = (self._device_call(  # flightcheck: disable=FC301
                "collect:prefill", jax.device_get, fetch)
                if fetch else [])
        except _DispatchFailed as e:
            self.time_prefill_s += time.perf_counter() - t0
            for ch in chs:
                if ch["toks"] is not None:
                    self._fail_prefill_group(ch["group"], e)
                for rid in ch["free_after"]:
                    self.dec.cache.free(rid)
            return
        self.time_prefill_s += time.perf_counter() - t0
        it = iter(fetched)
        for ch in chs:
            if ch["toks"] is not None:
                # re-wrap of the batched fetch above (already host
                # memory — the sync was paid at the designed point)
                self._prefill_complete(np.asarray(next(it)),  # flightcheck: disable=FC301
                                       ch["group"])
            for rid in ch["free_after"]:
                self.dec.cache.free(rid)

    def _fail_prefill_group(self, group, e: Exception):
        """Fail every request of an uncollectable final-prefill entry
        that is still waiting on it (epoch guard: requests restarted
        since the dispatch are someone else's problem now)."""
        for si, req, epoch in group:
            if req.state == "prefilling" and req.epoch == epoch:
                self._fail_request(
                    req, f"prefill collection failed after retries: {e}")

    def step(self) -> bool:
        """One engine iteration: admit, dispatch budget-bounded prefill
        chunks, dispatch the next decode chunk, then collect down to
        the pipeline depth (1 chunk stays in flight in overlap mode, so
        host admission/bookkeeping runs while the device decodes; the
        newest entry is the decode chunk whenever one was dispatched,
        so prefill results are always collected by the end of the step
        that could consume them). Returns True while there is still
        work. Fault tolerance: deadline enforcement runs first (an
        expired request never costs another dispatch); dispatch/fetch
        errors and KV pressure are absorbed inside the phases — step()
        itself never raises on a per-request fault."""
        self._enforce_deadlines()
        self._admit()
        if self.ragged:
            # unified ragged path: decode AND prefill rows ride ONE
            # device program per step (no separate prefill dispatches,
            # no merge dispatch)
            dispatched = self._dispatch_ragged()
        else:
            self._dispatch_prefill()
            dispatched = self._dispatch_chunk()
        # a speculative verify chunk is always collected THIS step
        # (depth 0): its accepted count decides the next schedule —
        # positions, slots and drafts — so there is nothing correct
        # to pipeline behind it
        depth = 1 if (dispatched and self.overlap
                      and not self._rep_active()
                      and not any(e["kind"] == "spec"
                                  for e in self._inflight)) else 0
        while len(self._inflight) > depth:
            # a RUN of leading prefill entries is fetched with one
            # batched device_get (one tunnel RTT per burst, not per
            # group); decode entries collect singly
            n = 0
            while (n < len(self._inflight) - depth
                   and self._inflight[n]["kind"] == "prefill"):
                n += 1
            if n > 1:
                self._collect_prefill_run(n)
            else:
                self._collect_oldest()
        if self.tracer is not None:
            # counter tracks (ISSUE 14): sample the scheduler gauges
            # into the trace every step so Perfetto renders resource
            # timelines next to the request spans
            self._sample_counter_tracks()
        if self._debug_pool:
            # PADDLE_TPU_POOL_DEBUG=1: assert the pool invariant
            # (free + cached + referenced == num_blocks, refs == table
            # contents, partial-prefill length bounds) after every
            # scheduler step — including between the chunks of a
            # multi-step prefill. With a lora registry, the adapter-
            # page invariants (use counts vs slots, page refs/hashes,
            # no zero-use allocations) ride the same sweep.
            self.dec.cache.debug_check()
            if self.lora is not None:
                self._debug_lora_check()
        return self.has_work

    def _sample_counter_tracks(self):
        """One sample per scheduler gauge per step (tracer attached):
        exported as Perfetto ``ph:"C"`` counter events, latest values
        mirrored as ``track.*`` registry gauges. Host scheduler state
        only — no device read, no schedule change."""
        tr = self.tracer
        pid = self.replica_id
        cache = self.dec.cache
        tr.counter("running_slots",
                   sum(1 for r in self._slots
                       if r is not None and r.state == "running"), pid)
        tr.counter("queue_depth", len(self._queue), pid)
        tr.counter("inflight_chunks", len(self._inflight), pid)
        tr.counter("free_blocks", cache.free_blocks, pid)
        tr.counter("cached_blocks", cache.cached_blocks, pid)
        if self.spec is not None:
            tr.counter("acceptance_ema", self.draft_acceptance_ema,
                       pid)
        if self.lora is not None:
            tr.counter("active_adapters", self.lora.active_count(),
                       pid)

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        """Drain the queue; returns {req_id: generated tokens}."""
        while self.step():
            pass
        return {rid: self.result(rid) for rid in list(self._done)}

    def warmup(self, prompt_len: Optional[int] = None,
               seal_programs: bool = False):
        """Pre-compile the serving programs — BOTH prefill widths for
        every bucket (or just prompt_len's bucket when given), the
        prefix-cache HIT prefill for every hit-reachable suffix bucket,
        plus the decode chunk — with throwaway requests, so no user
        request pays a compile. ``seal_programs=True`` additionally
        compiles the full reachable program grid (warmup_programs) and
        SEALS the set, so any later retrace counts as
        unexpected_recompiles (bound ragged_idle_cap first on ragged
        engines, or the grid is large). Prompts longer than prefill_chunk run
        the CHUNKED path (exactly as production traffic at that length
        will), compiling the no-sample chunk programs and the
        remainder-bucket finals instead of the monolithic full-length
        variants. Worth calling once at deployment; finished-request
        stats AND the prefix cache are cleared afterwards. Warns if
        the KV pool is too small to exercise the burst width (that
        variant would then compile on the first real burst)."""
        import warnings as _warnings
        plens = ([prompt_len] if prompt_len is not None
                 else list(self.buckets))
        cache = self.dec.cache
        width = min(self.PREFILL_GROUP, self.max_b)
        if self.max_b < 2:
            _warnings.warn(
                "warmup: max_batch_size < 2 — the burst prefill path "
                "never runs on this engine; only width-1 is warmed")
        for plen in plens:
            # phase 1: a single request — the width-1 program(s); a
            # plen past the chunk size compiles the chunk ladder
            self.add_request(self._warmup_prompt(plen),
                             SamplingParams(max_new_tokens=2))
            self.run_to_completion()
            if self.max_b < 2:
                continue
            # phase 2: a burst — the width-`width` program. The burst
            # path only runs if >= 2 requests admit TOGETHER.
            need = 2 * -(-(plen + 2) // cache.block_size)
            if cache.available_blocks < need:
                _warnings.warn(
                    f"warmup: pool too small to exercise the width-"
                    f"{width} prefill at bucket {plen} (need {need} "
                    "free pages); the first real burst there will pay "
                    "that compile")
                continue
            for _ in range(width):
                self.add_request(self._warmup_prompt(plen),
                                 SamplingParams(max_new_tokens=2))
            self.run_to_completion()
        # prefix-cache HIT programs: the suffix-prefix prefill compiles
        # per (suffix bucket, width), and warmup's distinct-fill miss
        # traffic never runs it — seed a one-block prefix, then admit
        # hits whose suffix lands in each reachable bucket (width 1),
        # plus one burst at the first reachable bucket (width `width`).
        # Suffixes past prefill_chunk take the chunked path here too,
        # warming the offset chunk program a long cache hit runs.
        if self.prefix_caching:
            bs = cache.block_size
            prefix = self._warmup_prompt(bs)
            seeded = burst_done = False

            def _hit_round(s_suf, rows):
                for _ in range(rows):
                    self.add_request(
                        np.concatenate([prefix,
                                        self._warmup_prompt(s_suf)]),
                        SamplingParams(max_new_tokens=2))
                self.run_to_completion()

            for b in self.buckets:
                s_suf = min(b, self.buckets[-1] - bs)
                if s_suf <= 0 or _bucket_for(s_suf, self.buckets) != b:
                    continue   # no runtime hit can land in this bucket
                per_hit = -(-(bs + s_suf + 2) // bs)
                if cache.available_blocks < per_hit + 1:
                    _warnings.warn(
                        f"warmup: pool too small to warm the prefix-hit "
                        f"prefill at suffix bucket {b}; the first real "
                        "hit there will pay that compile")
                    continue
                if not seeded:
                    # park the shared prefix block (suffix of 1 token)
                    self.add_request(
                        np.concatenate([prefix, self._warmup_prompt(1)]),
                        SamplingParams(max_new_tokens=1))
                    self.run_to_completion()
                    seeded = True
                _hit_round(s_suf, 1)
                if not burst_done and self.max_b >= 2 and \
                        cache.available_blocks >= width * per_hit:
                    _hit_round(s_suf, width)
                    burst_done = True
        # rich-sampling + plain decode programs, once per ladder chunk
        # size (each T is its own compiled program): top_k=1 is greedy,
        # so the rich throwaway is deterministic but routes through
        # _decode_rich_j. Spanning MULTIPLE decode chunks also compiles
        # the overlap-mode _merge_first_j chunk-to-chunk gather.
        warmed_rungs = set()
        for c in self.chunks:
            if -(-(plens[0] + c + 2) // cache.block_size) > \
                    cache.available_blocks:
                _warnings.warn(
                    f"warmup: pool too small to warm chunk rung {c}; "
                    f"its first real dispatch will pay the compile")
                continue
            warmed_rungs.add(c)
            # pin the rung: the heuristic could skip a middle rung whose
            # budget lands on a bigger one (its compile would then leak
            # into the timed cost loop below)
            self._force_chunk = c
            try:
                self.add_request(self._warmup_prompt(plens[0]),
                                 SamplingParams(max_new_tokens=c + 2,
                                                temperature=1.0,
                                                top_k=1))
                self.run_to_completion()
                self.add_request(self._warmup_prompt(plens[0]),
                                 SamplingParams(max_new_tokens=c + 2))
                self.run_to_completion()
            finally:
                self._force_chunk = None
        # measure each rung's steady chunk cost (compiles are done):
        # one request pinned to rung c for 3 chunks; the stall+host
        # delta over 3 chunks is the per-chunk cost _pick_chunk's
        # tokens/cost policy uses
        if len(self.chunks) > 1:
            for c in self.chunks:
                if c not in warmed_rungs:
                    # never time an un-warmed rung: the measurement
                    # would absorb its XLA compile and the rate policy
                    # would shun the rung forever
                    continue
                # clamp the measurement to the pool: a production pool
                # sized for small budgets must not fail warmup. Prefer
                # 3 chunks; fall back to fewer; skip the rung (leaving
                # it out of the cost table) if even one doesn't fit.
                n_chunks = 3
                while n_chunks > 0:
                    need = -(-(plens[0] + n_chunks * c)
                             // cache.block_size)
                    if need <= cache.available_blocks:
                        break
                    n_chunks -= 1
                if n_chunks == 0:
                    _warnings.warn(
                        f"warmup: pool too small to measure chunk rung "
                        f"{c} (needs {-(-(plens[0] + c) // cache.block_size)} "
                        f"free pages); rung left uncosted — the rate "
                        f"policy will not select it")
                    continue
                self._force_chunk = c
                try:
                    before = self.time_stall_s + self.time_host_s
                    self.add_request(
                        self._warmup_prompt(plens[0]),
                        SamplingParams(max_new_tokens=n_chunks * c))
                    self.run_to_completion()
                    delta = (self.time_stall_s + self.time_host_s
                             - before)
                finally:
                    self._force_chunk = None
                self._chunk_cost[c] = max(delta / n_chunks, 1e-6)
        # multi-tenant warmup (ISSUE 10): one short adapter-carrying
        # request compiles the lora ragged program family so the first
        # real tenant request pays no compile (base-only programs were
        # warmed above; an all-base dispatch never runs the lora
        # variant)
        if self.lora is not None and self.lora.ids():
            aid = self.lora.ids()[0]
            need = self.lora.n_pages() \
                + -(-(plens[0] + 2) // cache.block_size)
            if cache.available_blocks < need:
                _warnings.warn(
                    "warmup: pool too small to warm the lora serving "
                    "program; the first tenant request will pay that "
                    "compile")
            else:
                self.add_request(
                    self._warmup_prompt(plens[0]),
                    SamplingParams(max_new_tokens=2, adapter_id=aid))
                self.run_to_completion()
        # warmup traffic must leave no trace: parked throwaway blocks
        # would otherwise occupy LRU slots (and could in principle be
        # spliced by a real request with the same fill pattern) —
        # clear_prefix_cache also evicts warmup's parked adapter pages
        cache.clear_prefix_cache()
        if seal_programs:
            # close the remaining grid (rungs/widths the throwaway
            # traffic didn't reach) and declare the set sealed — from
            # here a mid-serving retrace is a counted, assertable bug
            self.warmup_programs()
            self.seal_programs()
        self.clear_finished()

    # -- program observatory: grid warmup + sealing (ISSUE 14) ---------------
    def reachable_ragged_widths(self, T: int,
                                max_width: Optional[int] = None
                                ) -> List[int]:
        """The W rungs a T-ministep ragged program can be dispatched
        at, derived from engine config: mixed-regime chunks carry at
        most max_b decode columns plus ceil(prefill_budget / T)
        prefill columns; pure-prefill chunks widen to the idle cap.
        Sticky-shrink only ever pads to a previously-reached width at
        the same T, so this set is CLOSED — compiling it whole is what
        makes seal_programs assertable."""
        cap = self._ragged_cap
        idle = max(cap, self._ragged_idle_cap)
        rows = max(self.max_b + -(-cap // T), -(-idle // T))
        return self._widths_up_to(rows, max_width)

    def _widths_up_to(self, rows: int,
                      max_width: Optional[int] = None) -> List[int]:
        """W rungs (the static ladder, then 64-multiples) reachable up
        to the padded width of ``rows`` — shared by the ragged and spec
        grids so the ladder/rounding rule can never drift between them
        (a one-sided change would make warmup_programs' grids disagree
        and seed sealed-set false positives)."""
        if max_width is not None:
            rows = min(rows, int(max_width))
        bound = self._ragged_width(rows)
        widths = [w for w in self.RAGGED_WIDTHS if w <= bound]
        w = (widths[-1] if widths else 0) + 64
        w -= w % 64
        while w <= bound:           # past-ladder 64-multiples
            widths.append(w)
            w += 64
        return widths

    def _spec_widths(self, max_width: Optional[int] = None
                     ) -> List[int]:
        """Reachable W rungs of the one-ministep speculative verify
        program: every running column fans out to 1 + draft_len rows,
        prefill rows fill what is left of the per-step budget."""
        rows = self.max_b * (1 + self.spec.draft_len) + self._ragged_cap
        return self._widths_up_to(rows, max_width)

    def warmup_programs(self, max_width: Optional[int] = None):
        """Compile the reachable serving-program grid by DIRECT
        program invocation — dummy operands aimed entirely at the
        scratch page/row, so no scheduler state changes, no pool block
        is claimed, and (unlike traffic-driven warmup) NO engine PRNG
        key is consumed: a warmed engine serves token-identical to an
        unwarmed one, stochastic sampling included. Every call routes
        through CompileWatch.observe, so the compiles land in the
        trace as compile spans; afterwards seal_programs() can declare
        the set closed. ``max_width`` clamps the ragged W rungs (tests
        use it to leave a rung cold on purpose)."""
        cache = self.dec.cache
        weights = self.dec.weights
        mb, mp, vocab = self.max_b, self.dec.max_pages, \
            self.dec.cfg.vocab_size
        aj = self._aj
        key1 = self._replicated(jax.random.PRNGKey(0))

        def obs(fn, *args):
            t0 = time.perf_counter()
            out = fn(*args)
            n_new, n_unexp = self.compile_watch.observe(
                fn, t0, time.perf_counter(), args)
            self.program_compiles += n_new
            self.unexpected_recompiles += n_unexp
            return out

        if not self.ragged:
            # dense per-phase programs: final prefill (plain + prefix
            # splice) per (bucket, width), the no-sample mid-chunk
            # ladder, the decode chunk rungs (+ rich twins) and the
            # overlap merge
            widths = sorted({1, min(self.PREFILL_GROUP, self.max_b)})
            for b in self.buckets:
                for w in widths:
                    ids = aj(np.zeros((w, b), np.int32))
                    slots = aj(np.full((w, b), self._scratch_slot,
                                       np.int32))
                    last_idx = aj(np.zeros(w, np.int32))
                    temps = aj(np.zeros(w, np.float32))
                    tks = aj(np.zeros(w, np.int32))
                    tps = aj(np.ones(w, np.float32))
                    reps = aj(np.ones(w, np.float32))
                    seen = self._zeros_seen(w, vocab)
                    allowed = self._ones_allowed(w, vocab)
                    _, cache.k, cache.v = obs(
                        self._prefill_j, weights, cache.k, cache.v,
                        ids, slots, last_idx, temps, key1, tks, tps,
                        reps, seen, allowed)
                    ncv = aj(np.zeros(w, np.int32))
                    ptab = aj(np.full((w, self._prefix_pages),
                                      self._scratch_block, np.int32))
                    _, cache.k, cache.v = obs(
                        self._prefill_prefix_j, weights, cache.k,
                        cache.v, ids, slots, last_idx, ncv, ptab,
                        temps, key1, tks, tps, reps, seen, allowed)
            if self._can_recompute:
                c = self.prefill_chunk or self._recompute_chunk
                ids1 = aj(np.zeros((1, c), np.int32))
                slots1 = aj(np.full((1, c), self._scratch_slot,
                                    np.int32))
                cache.k, cache.v = obs(self._prefill_mid0_j, weights,
                                       cache.k, cache.v, ids1, slots1)
                for pb in self._prefix_page_buckets:
                    ptab = aj(np.full((1, pb), self._scratch_block,
                                      np.int32))
                    cache.k, cache.v = obs(
                        self._prefill_mid_j, weights, cache.k, cache.v,
                        ids1, slots1, aj(np.asarray([1], np.int32)),
                        ptab)
            for T in self.chunks:
                first = aj(np.zeros(mb, np.int32))
                tables = aj(np.full((T, mb, mp), self._scratch_block,
                                    np.int32))
                ctx = aj(np.zeros((T, mb), np.int32))
                slots = aj(np.full((T, mb), self._scratch_slot,
                                   np.int32))
                temps = aj(np.zeros(mb, np.float32))
                keys = jax.random.split(jax.random.PRNGKey(0), T)
                toks, cache.k, cache.v = obs(
                    self._decode_j, weights, cache.k, cache.v, first,
                    tables, ctx, slots, temps, keys)
                obs(self._merge_first_j, toks, aj(np.zeros(mb,
                    np.int32)), aj(np.zeros(mb, np.int32)),
                    aj(np.ones(mb, bool)))
                _, cache.k, cache.v = obs(
                    self._decode_rich_j, weights, cache.k, cache.v,
                    first, tables, ctx, slots, temps, keys,
                    aj(np.zeros(mb, np.int32)),
                    aj(np.ones(mb, np.float32)),
                    aj(np.ones(mb, np.float32)),
                    self._zeros_seen(mb, vocab),
                    self._ones_allowed(mb, vocab))
            return

        # ragged grid: every (T, W) variant of the unified chunk (+
        # rich and lora twins where configured), then the spec verify
        # widths. All rows are scratch rows (rctx 0), exactly the
        # schedule shape an all-neutralized production chunk ships.
        scratch_row = mb
        lora_pre = ()
        if self.lora is not None:
            lora_pre = (cache.lora_pool, self._shard_ids,
                        aj(np.full((mb + 1, self.lora.n_pages()),
                                   self._scratch_block, np.int32)))
        def ragged_tail(T, W):
            z2 = np.zeros((T, W), np.int32)
            return (self._zeros_toks(T, W),
                    aj(np.zeros(W, np.int32)),
                    aj(np.zeros(W, np.int32)),
                    aj(np.ones(W, bool)),
                    aj(np.zeros(W, np.int32)),
                    aj(z2), aj(z2),
                    aj(np.full((T, W), self._scratch_slot, np.int32)),
                    aj(np.full((T, W), scratch_row, np.int32)),
                    aj(z2),
                    aj(np.zeros((T, W), bool)),
                    aj(np.full((mb + 1, mp), self._scratch_block,
                               np.int32)),
                    aj(np.zeros((T, W), np.float32)),
                    self._replicated(
                        jax.random.split(jax.random.PRNGKey(0), T)))

        def ragged_rich_tail(T, W):
            return (aj(np.zeros((T, W), np.int32)),
                    aj(np.ones((T, W), np.float32)),
                    aj(np.ones((T, W), np.float32)),
                    self._zeros_seen(W, vocab),
                    aj(np.zeros(W, bool)),
                    self._ones_allowed(W, vocab))

        for T in sorted(set(list(self.chunks) + [1])):
            for W in self.reachable_ragged_widths(T, max_width):
                tail = ragged_tail(T, W)
                _, cache.k, cache.v = obs(
                    self._ragged_j, weights, cache.k, cache.v, *tail)
                rich_tail = ragged_rich_tail(T, W)
                _, cache.k, cache.v = obs(
                    self._ragged_rich_j, weights, cache.k, cache.v,
                    *tail, *rich_tail)
                if self.lora is not None:
                    _, cache.k, cache.v = obs(
                        self._ragged_lora_j, weights, cache.k,
                        cache.v, *lora_pre, *tail)
                    _, cache.k, cache.v = obs(
                        self._ragged_lora_rich_j, weights, cache.k,
                        cache.v, *lora_pre, *tail, *rich_tail)
        if self.multi_step > 1:
            # the (T, W, k) grid (ISSUE 16): fused windows dispatch at
            # k x the chunk rung picked over running slots, and only
            # in the pure-decode regime — but sticky-shrink can pad a
            # window up to ANY width the same window length reached
            # (including a prefill-widened single-step chunk when
            # k*chunk collides with a chunk rung), so the fused
            # families compile the full reachable width set per rung.
            # Scratch-aimed operands like the base grid; eos -1 = the
            # no-EOS schedule every all-neutralized window ships.
            for T in sorted({self.multi_step * c for c in self.chunks}):
                for W in self.reachable_ragged_widths(T, max_width):
                    tail = ragged_tail(T, W)
                    eos = aj(np.full(W, -1, np.int32))
                    _, cache.k, cache.v = obs(
                        self._ragged_ms_j, weights, cache.k, cache.v,
                        *tail, eos)
                    rich_tail = ragged_rich_tail(T, W)
                    _, cache.k, cache.v = obs(
                        self._ragged_ms_rich_j, weights, cache.k,
                        cache.v, *tail, eos, *rich_tail)
                    if self.lora is not None:
                        _, cache.k, cache.v = obs(
                            self._ragged_ms_lora_j, weights, cache.k,
                            cache.v, *lora_pre, *tail, eos)
                        _, cache.k, cache.v = obs(
                            self._ragged_ms_lora_rich_j, weights,
                            cache.k, cache.v, *lora_pre, *tail, eos,
                            *rich_tail)
        if self.spec is not None:
            for W in self._spec_widths(max_width):
                z1 = np.zeros(W, np.int32)
                spec_tail = (
                    aj(z1), aj(np.zeros(W, bool)), aj(z1), aj(z1),
                    aj(np.full(W, self._scratch_slot, np.int32)),
                    aj(np.full(W, scratch_row, np.int32)), aj(z1),
                    aj(np.full((mb + 1, mp), self._scratch_block,
                               np.int32)),
                    aj(np.zeros(W, np.float32)), key1,
                    aj(np.arange(W, dtype=np.int32)),
                    aj(np.zeros(W, bool)))
                _, _, cache.k, cache.v = obs(
                    self._spec_j, weights, cache.k, cache.v,
                    *spec_tail)
                if self.lora is not None:
                    _, _, cache.k, cache.v = obs(
                        self._spec_lora_j, weights, cache.k, cache.v,
                        *lora_pre, *spec_tail)

    def seal_programs(self):
        """Declare the compiled program set COMPLETE (call after
        warmup_programs, or after a steady-state lap whose program set
        is the production one): from here on, any compile observed by
        the watch increments stats()["unexpected_recompiles"] and
        fires an ``unexpected_recompile`` tracer event — the runtime
        FC2xx. Chaos legs and bench.py serving_trace assert zero."""
        self.compile_watch.seal()

    def clear_finished(self):
        """Drop finished requests + counters (e.g. after warmup) so
        stats() reflect only the workload that follows — including the
        prefix-cache hit/eviction counters and the ITL/utilization
        accounting, so warmup traffic cannot pollute the reported
        numbers."""
        self._done.clear()
        self.decode_steps = 0
        self.generated_tokens = 0
        self.decode_slot_steps = 0
        self.decode_useful_tokens = 0
        self.time_prefill_s = 0.0
        self.time_stall_s = 0.0
        self.time_host_s = 0.0
        # robustness counters reset alongside the prefix-cache ones so
        # a post-warmup stats() reflects only real traffic
        self.preemptions = 0
        self.recompute_tokens = 0
        self.aborted = 0
        self.failed = 0
        self.deadline_misses = 0
        self.shed_requests = 0
        self.retries = 0
        self.dispatch_exhaustions = 0
        self.device_dispatches = 0
        self.drafted_tokens = 0
        self.accepted_draft_tokens = 0
        self.spec_rollbacks = 0
        # multi-tenant counters reset alongside everything else
        self.lora_dispatches = 0
        self.lora_rows = 0
        self.masked_decode_columns = 0
        # multi-step fused-decode counters (ISSUE 16); the multi_step
        # gauge itself is engine config and survives, like kv_quant
        self.ms_windows = 0
        self.ms_frozen_token_waste = 0
        # program-observatory counters (ISSUE 14): the engine-side
        # view resets with every other counter family; the
        # CompileWatch's own cumulative ledger (and its sealed flag)
        # survives — the program set is an engine property, not a
        # workload one
        self.unexpected_recompiles = 0
        self.program_compiles = 0
        self.profiled_dispatches = 0
        self.draft_acceptance_ema = 0.0
        if self._slo is not None:
            self._slo.reset()
        self._slo_violating.clear()
        # the memo keys masks by object identity; retained requests
        # (and their masks) are dropped here, so the memo must go too
        # (a recycled id must never alias a dead request's operand)
        self._allowed_memo.clear()
        # finished-request ITL reservoir resets with the requests it
        # sampled (same seed: identical runs keep identical stats)
        self._itl_res = Reservoir(self.ITL_RESERVOIR_K)
        if self.lora is not None:
            self.lora.reset_stats()
        self.dec.cache.reset_prefix_stats()

    def stats(self) -> dict:
        """Latency/throughput summary over finished requests.

        Timing keys:
        - latency/ttft percentiles: per-request wall clocks.
        - itl_p50_s / itl_p99_s: inter-token latency — each collected
          decode chunk's wall interval split evenly over the tokens it
          delivered to a request (chunks of T tokens arrive together;
          the per-token attribution is T-ths of the gap, the standard
          chunked-serving convention). The headline metric for
          chunked prefill: a long prompt admitted mid-stream must not
          spike running requests' ITL. Aggregated over successfully
          finished AND currently-running requests (aborted/failed
          lifetimes are excluded, like the other percentiles).
        - queue_wait_p50_s: submit → batch-slot admission.
        - time_prefill_s / time_decode_stall_s / time_host_s: wall
          time of the engine's blocking call sites. Prefill results
          are fetched at collection time in device order (never inside
          admission), so a prefill fetch waits only on work dispatched
          BEFORE it — the old overlap caveat (a blocking prefill fetch
          silently absorbing in-flight decode time) is gone; the one
          residual coupling is that the device runs a single queue, so
          the oldest entry's fetch covers any earlier entries still
          executing.

        Utilization keys (chunk-ladder tuning): a decode dispatch runs
        T steps x max_batch slots regardless of real work —
        padded_token_waste counts slot-steps that produced no delivered
        token (inactive slots, budget-drained tails, post-EOS
        discards), decode_utilization = delivered / slot-steps."""
        cache = self.dec.cache
        ok = [r for r in self._done.values() if r.state == "done"]
        lats = [r.latency_s for r in ok if r.latency_s is not None]
        ttfts = [r.ttft_s for r in ok if r.ttft_s is not None]
        waits = [r.queue_wait_s for r in ok
                 if r.queue_wait_s is not None]
        # terminal side filtered to state=="done" like lats/ttfts/waits
        # above: an aborted/failed request's stall-inflated gaps must
        # not bleed into the successful-traffic ITL percentiles.
        # Finished requests' samples come from the bounded reservoir
        # (fed at _retire — done-state lifetimes only); live slotted
        # requests' samples are read exactly. Exact below the reservoir
        # capacity, sampling-tolerance beyond it (ISSUE 12 satellite:
        # the raw union list grew without limit on long runs).
        itls = list(self._itl_res) + [
            x for r in self._slots if r is not None for x in r.itls]

        def pct(xs, p):
            # Interpolated (the truncating index form overstated
            # p50/p99 on small samples).
            return float(np.quantile(xs, p)) if xs else None

        out = {
            # finished = completed successfully; aborted/failed/shed
            # are accounted separately below (latency/TTFT percentiles
            # cover successful requests only — a deadline abort's
            # truncated lifetime must not flatter the percentiles)
            "finished": len(ok),
            # -- robustness counters (reset by clear_finished) --------
            "preemptions": self.preemptions,
            "recompute_tokens": self.recompute_tokens,
            "aborted": self.aborted,
            "failed": self.failed,
            "deadline_misses": self.deadline_misses,
            "shed_requests": self.shed_requests,
            "retries": self.retries,
            "dispatch_exhaustions": self.dispatch_exhaustions,
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "latency_p50_s": pct(lats, 0.50),
            "latency_p99_s": pct(lats, 0.99),
            "ttft_p50_s": pct(ttfts, 0.50),
            "ttft_p99_s": pct(ttfts, 0.99),
            "itl_p50_s": pct(itls, 0.50),
            "itl_p99_s": pct(itls, 0.99),
            "queue_wait_p50_s": pct(waits, 0.50),
            "time_prefill_s": self.time_prefill_s,
            "time_decode_stall_s": self.time_stall_s,
            "time_host_s": self.time_host_s,
            # device-program launches and delivered tokens per launch —
            # the ragged path's headline: one program per step instead
            # of merge + decode + N prefill dispatches. Accepted draft
            # tokens are generated_tokens like any other delivered
            # token, so speculative decoding's win shows up here
            # directly (a verify dispatch delivers up to draft_len + 1
            # tokens per column). Under multi_step=k a fused window is
            # ONE launch delivering up to k*T tokens per column —
            # decode_steps/slot_steps count its per-iteration rows
            # (entry "T" carries the window length), so this ratio and
            # the waste terms below stay per-ministep honest.
            "device_dispatches": self.device_dispatches,
            "tokens_per_dispatch": (
                self.generated_tokens / self.device_dispatches
                if self.device_dispatches else 0.0),
            # -- speculative decoding (reset by clear_finished) -------
            "drafted_tokens": self.drafted_tokens,
            "accepted_draft_tokens": self.accepted_draft_tokens,
            "draft_acceptance_rate": (
                self.accepted_draft_tokens / self.drafted_tokens
                if self.drafted_tokens else 0.0),
            "spec_rollbacks": self.spec_rollbacks,
            # -- multi-tenant LoRA serving (reset by clear_finished) --
            # active_adapters: adapters pinned by >= 1 slotted request
            # right now; hits/misses/evictions: registry residency
            # traffic (hit = ref-bump or LRU revive, miss = fault-in
            # upload, eviction = a previously-resident adapter found
            # evicted at re-acquire); lora_rows_per_dispatch: ragged
            # rows that carried a real adapter per lora dispatch — the
            # mixed-tenant batching density; masked_decode_columns:
            # scheduled decode columns under an allowed_tokens mask
            "active_adapters": (self.lora.active_count()
                                if self.lora is not None else 0),
            "adapter_cache_hits": (self.lora.hits
                                   if self.lora is not None else 0),
            "adapter_cache_misses": (self.lora.misses
                                     if self.lora is not None else 0),
            "adapter_cache_evictions": (
                self.lora.evictions if self.lora is not None else 0),
            "lora_rows_per_dispatch": (
                self.lora_rows / self.lora_dispatches
                if self.lora_dispatches else 0.0),
            "masked_decode_columns": self.masked_decode_columns,
            # -- multi-step fused decode (ISSUE 16) -------------------
            # multi_step_k: the engine's configured window depth (a
            # config gauge, like kv_quant — clear_finished leaves it);
            # multi_step_windows: fused windows dispatched;
            # ms_frozen_token_waste: slot-steps scheduled into fused
            # windows but frozen by an in-window EOS (a subset of
            # padded_token_waste — the honest cost of running EOS
            # bookkeeping on device instead of re-planning every step)
            "multi_step_k": float(self.multi_step),
            "multi_step_windows": self.ms_windows,
            "ms_frozen_token_waste": self.ms_frozen_token_waste,
            "decode_slot_steps": self.decode_slot_steps,
            # ragged-aware: on the ragged path slot_steps counts the
            # [T, W] grid actually dispatched (W sized by real rows)
            # and useful tokens include dispatched prefill rows, so
            # this is the true pad-to-grid remainder (plus post-EOS
            # discards) — the dense path's scratch-slot waste term is
            # structurally gone there
            "padded_token_waste": (self.decode_slot_steps
                                   - self.decode_useful_tokens),
            "decode_utilization": (
                self.decode_useful_tokens / self.decode_slot_steps
                if self.decode_slot_steps else 0.0),
            # prefix cache: hit tokens = prompt tokens whose KV was
            # spliced from cached blocks instead of re-prefilled;
            # hit rate is over all prompt tokens seen at admission
            "prefix_cache_hit_tokens": cache.prefix_hit_tokens,
            "prefix_cache_hit_rate": (
                cache.prefix_hit_tokens / cache.prefix_query_tokens
                if cache.prefix_query_tokens else 0.0),
            "prefix_cache_evictions": cache.prefix_evictions,
            "free_blocks": cache.free_blocks,
            "cached_blocks": cache.cached_blocks,
            # -- quantized KV cache (ISSUE 13) ------------------------
            # kv_quant: the pool's storage mode ("fp32"-family dtype
            # name or "int8"); kv_pool_bytes / kv_bytes_per_token: the
            # pool's logical device footprint (sidecar scales
            # included) — the capacity headline the int8 pool roughly
            # halves. Pool-geometry gauges: clear_finished leaves them
            # at the same recomputed values (pinned by the reset test)
            # while every counter around them drops to zero.
            "kv_quant": self.kv_quant or cache.pool_dtype,
            "kv_pool_bytes": cache.pool_bytes(),
            "kv_bytes_per_token": cache.bytes_per_token(),
            # -- program observatory (ISSUE 14) -----------------------
            # program_compiles: trace+lower+compile events the watch
            # observed (warmup's grid lands here); unexpected_
            # recompiles: compiles AFTER seal_programs() — the runtime
            # FC2xx, asserted zero by chaos legs and the bench;
            # profiled_dispatches: sampled-attribution fences taken;
            # draft_acceptance_ema: the per-window acceptance EMA the
            # acceptance_ema counter track samples (adaptive-window
            # signal for ROADMAP 2)
            "program_compiles": self.program_compiles,
            "unexpected_recompiles": self.unexpected_recompiles,
            "programs_sealed": self.compile_watch.sealed,
            "profiled_dispatches": self.profiled_dispatches,
            "draft_acceptance_ema": float(self.draft_acceptance_ema),
        }
        if self._slo is not None:
            # declared-SLO evaluation over the sliding windows: per
            # policy/metric burn rates + headroom (telemetry.
            # SLOMonitor.evaluate); the fleet Router rolls the
            # per-replica headrooms up for SLO-aware routing. The
            # nested dict rides stats() only; the scalar
            # slo_min_headroom mirrors into the registry like every
            # other float
            slo = self._slo.evaluate()
            out["slo"] = slo
            out["slo_min_headroom"] = float(slo["min_headroom"])
            if self.tracer is not None:
                for pname, pol in slo["policies"].items():
                    if pol["violating"] and \
                            pname not in self._slo_violating:
                        self.tracer.event(
                            "slo_violation", pid=self.replica_id,
                            policy=pname, headroom=pol["headroom"])
                self._slo_violating = {
                    pname for pname, pol in slo["policies"].items()
                    if pol["violating"]}
                flat = {}
                for pname, pol in slo["policies"].items():
                    flat[f"{pname}.headroom"] = float(pol["headroom"])
                    for metric, md in pol["metrics"].items():
                        for wname, wd in md["windows"].items():
                            if wd["burn_rate"] is not None:
                                flat[f"{pname}.{metric}."
                                     f"burn_{wname}"] = \
                                    float(wd["burn_rate"])
                prefix = ("slo" if self.replica_id == 0
                          else f"slo.r{self.replica_id}")
                self.tracer.metrics.publish(prefix, flat)
        if self.tracer is not None:
            # the unified metrics registry mirrors this dict (ints ->
            # counters, floats -> gauges), so the stats() view and the
            # registry agree bit-for-bit — the cross-subsystem rollup
            # tests pin the parity. In a fleet the tracer is SHARED:
            # each replica publishes under its own namespace ("engine"
            # for replica 0 / a single engine, "engine1"... beyond),
            # so one replica's counters never masquerade as another's;
            # fleet-wide totals live under "fleet.*" and the shared
            # engine.itl_s/ttft_s/latency_s histograms ACCUMULATE
            # across replicas (a fleet-wide distribution by design).
            prefix = ("engine" if self.replica_id == 0
                      else f"engine{self.replica_id}")
            self.tracer.metrics.publish(prefix, out)
        return out

    # -- shutdown (ISSUE 19) -------------------------------------------------
    def close(self):
        """Graceful shutdown: collect every in-flight device chunk so
        dispatched buffers retire deterministically (nothing is left
        referencing pool pages), then mark the engine closed.
        Idempotent — a second close is a no-op; step()/add_request
        after close are not supported. The fleet transports call this
        from Router.close(), and a worker process calls it on its way
        out of the command loop."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            while self._inflight:
                self._collect_oldest()
        except Exception:       # noqa: BLE001 — shutdown path: a torn
            # collection must not keep the process alive; drop the
            # remaining entries (their requests stay non-terminal)
            self._inflight.clear()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
